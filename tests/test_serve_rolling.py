"""Round-2/3 serve done-criterion: an HTTP client streams tokens from a
2-node cluster WHILE a rolling update replaces the replicas; the
in-flight stream finishes on the old version (drain) and later requests
see the new version. Also pins the SSE per-item timeout guard.

Ref analogue: serve/_private/proxy.py streaming + deployment_state.py
rolling update with graceful drain."""

import json
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.cluster_utils import Cluster


@pytest.fixture
def two_node_serve():
    c = Cluster(head_resources={"CPU": 2},
                system_config={"log_to_driver": False})
    c.add_node(num_cpus=2)
    c.wait_for_nodes(2)
    yield c
    serve.shutdown()
    c.shutdown()


def _sse_events(resp):
    """Parse `data:` frames incrementally from a streaming HTTP response."""
    buf = b""
    while True:
        chunk = resp.read1(4096) if hasattr(resp, "read1") else resp.read(4096)
        if not chunk:
            return
        buf += chunk
        while b"\n\n" in buf:
            frame, buf = buf.split(b"\n\n", 1)
            for line in frame.splitlines():
                if line.startswith(b"data: "):
                    yield json.loads(line[6:])
            if frame.startswith(b"event: end"):
                return


def test_stream_through_rolling_update(two_node_serve):
    from ray_tpu.serve import http_proxy

    def make(version):
        @serve.deployment(num_replicas=2)
        class Tok:
            def stream(self, n):
                for i in range(int(n)):
                    time.sleep(0.12)
                    yield {"v": version, "i": i}

            def __call__(self, _):
                return {"v": version}

        return Tok

    serve.run(make("v1").bind(), name="tok")
    proxies = http_proxy.start_per_node_proxies(port=0)
    try:
        assert len(proxies) >= 2, "expected a proxy on every node"
        ports = [p for _, p in proxies.values()]

        req = urllib.request.Request(
            f"http://127.0.0.1:{ports[0]}/tok/stream",
            data=json.dumps(20).encode(),
            headers={"Content-Type": "application/json",
                     "Accept": "text/event-stream"},
        )
        resp = urllib.request.urlopen(req, timeout=60)
        events = _sse_events(resp)
        first = next(events)
        assert first == {"v": "v1", "i": 0}

        # Mid-stream: roll the deployment to v2 (new code version).
        serve.run(make("v2").bind(), name="tok")

        rest = list(events)
        got = [first] + [e for e in rest if e is not None]
        # The in-flight stream finished on the OLD version — the rolling
        # update drained the replica instead of killing it mid-stream.
        assert [e["i"] for e in got] == list(range(20))
        assert all(e["v"] == "v1" for e in got), got[-3:]

        # New requests (via the OTHER node's proxy) see the new version.
        deadline = time.time() + 60
        while time.time() < deadline:
            req2 = urllib.request.Request(
                f"http://127.0.0.1:{ports[1]}/tok",
                data=json.dumps(None).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req2, timeout=30) as r2:
                body = json.loads(r2.read())
            if body.get("result", {}).get("v") == "v2":
                break
            time.sleep(0.25)
        assert body["result"]["v"] == "v2", body
    finally:
        for actor, _ in proxies.values():
            try:
                ray_tpu.get(actor.shutdown.remote(), timeout=10)
                ray_tpu.kill(actor)
            except Exception:
                pass


def test_stream_item_timeout_guard():
    """A wedged replica generator surfaces a timeout to the consumer
    instead of pinning it forever (handle.stream item deadline)."""
    ray_tpu.init(num_cpus=2, system_config={"log_to_driver": False})
    try:
        from ray_tpu.serve import handle as handle_mod

        @serve.deployment
        class Wedge:
            def stream(self, _):
                yield {"i": 0}
                time.sleep(3600)  # never yields again
                yield {"i": 1}

        h = serve.run(Wedge.bind(), name="wedge").options(method="stream")
        old = handle_mod.STREAM_ITEM_TIMEOUT_S
        handle_mod.STREAM_ITEM_TIMEOUT_S = 2.0
        try:
            it = h.stream(None)
            assert next(it) == {"i": 0}
            t0 = time.time()
            with pytest.raises(Exception):
                next(it)
            assert time.time() - t0 < 30
        finally:
            handle_mod.STREAM_ITEM_TIMEOUT_S = old
    finally:
        serve.shutdown()
        ray_tpu.shutdown()
