"""Ops layer tests: state API, job submission, CLI, log monitor, driver
attach (ref analogue: python/ray/tests/test_state_api.py +
dashboard/modules/job/tests + test_cli.py)."""

import json
import os
import subprocess
import sys
import time

import pytest

import ray_tpu


def test_state_api_lists_tasks_actors_objects(ray_tpu_start):
    from ray_tpu.util import state as state_api

    @ray_tpu.remote
    class Holder:
        def __init__(self):
            self.x = 1

        def get(self):
            return self.x

    holders = [Holder.remote() for _ in range(2)]
    ray_tpu.get([h.get.remote() for h in holders])
    ref = ray_tpu.put(b"x" * 4096)

    actors = state_api.list_actors()
    alive = [a for a in actors if a["state"] == "alive"]
    assert len(alive) >= 2
    assert all(a["class_name"].startswith("Holder") for a in alive)
    assert all(a["pid"] is not None for a in alive)

    objs = state_api.list_objects()
    assert any(o["size_bytes"] >= 4096 for o in objs)
    del ref

    workers = state_api.list_workers()
    assert len(workers) >= 1
    assert state_api.list_nodes()[0]["Alive"] is True

    summ = state_api.summarize_actors()
    assert summ.get("alive", 0) >= 2

    # Filters narrow results.
    dead = state_api.list_actors(filters=[("state", "=", "dead")])
    assert all(a["state"] == "dead" for a in dead)


def test_job_submission_end_to_end(ray_tpu_start):
    from ray_tpu.job_submission import JobStatus, JobSubmissionClient

    client = JobSubmissionClient()
    job_id = client.submit_job(
        entrypoint=f"{sys.executable} -c \"print('hello from job')\""
    )
    status = client.wait_until_finish(job_id, timeout=60)
    assert status == JobStatus.SUCCEEDED
    assert "hello from job" in client.get_job_logs(job_id)
    assert job_id in client.list_jobs()


def test_job_failure_and_stop(ray_tpu_start):
    from ray_tpu.job_submission import JobStatus, JobSubmissionClient

    client = JobSubmissionClient()
    bad = client.submit_job(entrypoint=f"{sys.executable} -c 'raise SystemExit(3)'")
    assert client.wait_until_finish(bad, timeout=60) == JobStatus.FAILED

    slow = client.submit_job(
        entrypoint=f"{sys.executable} -c 'import time; time.sleep(600)'"
    )
    deadline = time.monotonic() + 30
    while (client.get_job_status(slow) != JobStatus.RUNNING
           and time.monotonic() < deadline):
        time.sleep(0.1)
    assert client.stop_job(slow)
    assert client.get_job_status(slow) == JobStatus.STOPPED


def test_log_monitor_streams_worker_output(capfd):
    """Task print() output reaches the driver with (pid=, node=) prefixes
    (ref: log_monitor.py streaming). Initializes inside the test so the
    monitor's output lands in capfd's capture window."""
    ray_tpu.init(num_cpus=2)

    @ray_tpu.remote
    def chatty():
        print("marker-from-worker-xyz")
        return 1

    assert ray_tpu.get(chatty.remote()) == 1
    deadline = time.monotonic() + 10
    seen = ""
    while time.monotonic() < deadline:
        seen += capfd.readouterr().out
        if "marker-from-worker-xyz" in seen:
            break
        time.sleep(0.2)
    ray_tpu.shutdown()
    assert "marker-from-worker-xyz" in seen
    line = next(l for l in seen.splitlines()
                if "marker-from-worker-xyz" in l)
    assert "(pid=" in line and "node=" in line


CLI = [sys.executable, "-m", "ray_tpu.scripts.cli"]



def test_cli_cluster_lifecycle(tmp_path):
    """rtpu start --head → status → submit → stop against a real detached
    head process (ref: `ray start/status/job submit/stop`)."""
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    env = dict(os.environ)
    env.pop("RAY_TPU_ADDRESS", None)
    head = subprocess.Popen(
        CLI + ["start", "--head", "--block", "--port", str(port),
               "--num-cpus", "2"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
    )
    try:
        address = f"127.0.0.1:{port}"
        deadline = time.monotonic() + 30
        up = False
        while time.monotonic() < deadline:
            try:
                with socket.create_connection(("127.0.0.1", port),
                                              timeout=1):
                    up = True
                    break
            except OSError:
                time.sleep(0.2)
        assert up, "head never opened its GCS port"

        out = subprocess.run(
            CLI + ["status", "--address", address], env=env,
            capture_output=True, text=True, timeout=60,
        )
        assert out.returncode == 0, out.stdout + out.stderr
        assert "alive" in out.stdout

        script = tmp_path / "job.py"
        script.write_text(
            "import ray_tpu\n"
            "ray_tpu.init()\n"  # attaches via RAY_TPU_ADDRESS from the job env
            "@ray_tpu.remote\n"
            "def f(x):\n"
            "    return x * 2\n"
            "print('job-result', ray_tpu.get(f.remote(21)))\n"
        )
        out = subprocess.run(
            CLI + ["submit", "--address", address, "--",
                   sys.executable, str(script)],
            env=env, capture_output=True, text=True, timeout=120,
        )
        assert out.returncode == 0, out.stdout + out.stderr
        assert "job-result 42" in out.stdout
    finally:
        head.terminate()
        try:
            head.wait(timeout=10)
        except subprocess.TimeoutExpired:
            head.kill()


def test_dashboard_serves_state(ray_tpu_start):
    """The dashboard's JSON API mirrors the state API (ref: dashboard
    modules)."""
    import urllib.request

    from ray_tpu import dashboard

    @ray_tpu.remote
    class Pinger:
        def ping(self):
            return 1

    p = Pinger.remote()
    ray_tpu.get(p.ping.remote())
    port = dashboard.start_dashboard(port=0)
    try:
        def fetch(path):
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{path}", timeout=30) as r:
                return json.loads(r.read())

        nodes = fetch("/api/nodes")
        assert nodes and nodes[0]["Alive"]
        actors = fetch("/api/actors")
        assert any(a["class_name"] == "Pinger" for a in actors)
        summary = fetch("/api/summary/actors")
        assert summary.get("alive", 0) >= 1
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/", timeout=30) as r:
            assert b"ray_tpu" in r.read()
    finally:
        dashboard.stop_dashboard()


def test_timeline_export(ray_tpu_start, tmp_path):
    """ray_tpu.timeline() exports chrome-trace task spans from every
    worker (ref: ray.timeline)."""
    @ray_tpu.remote
    def traced_work(x):
        time.sleep(0.05)
        return x

    ray_tpu.get([traced_work.remote(i) for i in range(4)])
    out = str(tmp_path / "trace.json")
    deadline = time.monotonic() + 10
    events = []
    while time.monotonic() < deadline:
        events = ray_tpu.timeline(out)
        # Workers flush their buffers independently — wait for ALL four
        # spans, not the first flusher's subset.
        if sum(e["name"] == "traced_work" for e in events) >= 4:
            break
        time.sleep(0.2)
    spans = [e for e in events if e["name"] == "traced_work"]
    assert len(spans) == 4
    assert all(e["ph"] == "X" and e["dur"] >= 0.04 * 1e6 for e in spans)
    with open(out) as f:
        assert json.load(f)


def test_prometheus_metrics_endpoint(ray_tpu_start):
    """`curl :<port>/metrics` returns Prometheus text format with core
    counters that MOVE under load plus user metrics (VERDICT r3 ask #4;
    ref: _private/prometheus_exporter.py)."""
    import re
    import urllib.request

    from ray_tpu import dashboard
    from ray_tpu.util.metrics import Counter, Histogram

    port = dashboard.start_dashboard(port=0)

    def scrape():
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=30) as r:
            assert r.headers["Content-Type"].startswith("text/plain")
            return r.read().decode()

    def counter_value(text, name):
        m = re.search(rf"^{name} (\d+)", text, re.M)
        assert m, f"{name} missing from exposition:\n{text[:800]}"
        return int(m.group(1))

    try:
        before = scrape()
        for metric in ("ray_tpu_tasks_submitted_total",
                       "ray_tpu_tasks_finished_total",
                       "ray_tpu_workers_alive",
                       "ray_tpu_object_store_used_bytes"):
            assert metric in before, metric
        t0 = counter_value(before, "ray_tpu_tasks_finished_total")

        @ray_tpu.remote
        def work(i):
            return i

        ray_tpu.get([work.remote(i) for i in range(50)])
        c = Counter("app_requests", tag_keys=("route",))
        c.inc(3, tags={"route": "/x"})
        h = Histogram("app_latency_s", boundaries=[0.1, 1.0])
        h.observe(0.05)
        h.observe(5.0)
        time.sleep(0.7)  # metric flush interval

        after = scrape()
        t1 = counter_value(after, "ray_tpu_tasks_finished_total")
        assert t1 >= t0 + 50, (t0, t1)
        assert 'app_requests_total{route="/x"} 3' in after, after[-500:]
        assert 'app_latency_s_bucket{le="0.1"} 1' in after
        assert 'app_latency_s_bucket{le="+Inf"} 2' in after
        assert "app_latency_s_count 2" in after
    finally:
        dashboard.stop_dashboard()


def test_trace_span_tree(tmp_path):
    """Spans around submit/execute with context propagated through the
    TaskSpec: one trace shows a driver-submit -> worker-exec ->
    nested-task span tree (VERDICT r3 ask #9; ref:
    util/tracing/tracing_helper.py:326)."""
    import importlib
    import os
    import subprocess
    import sys

    # RAY_TPU_TRACE_SUBMITS is read at import: run in a fresh process.
    code = r"""
import json, sys
import ray_tpu

ray_tpu.init(num_cpus=2, system_config={"log_to_driver": False})

@ray_tpu.remote
def child(x):
    return x + 1

@ray_tpu.remote
def parent_task(x):
    return ray_tpu.get(child.remote(x)) * 10

assert ray_tpu.get(parent_task.remote(4), timeout=60) == 50
trace = ray_tpu.timeline()
ray_tpu.shutdown()
json.dump(trace, open(sys.argv[1], "w"))
"""
    out = tmp_path / "trace.json"
    env = dict(os.environ, RAY_TPU_TRACE_SUBMITS="1")
    subprocess.run([sys.executable, "-c", code, str(out)], check=True,
                   env=env, timeout=300)
    trace = json.load(open(out))
    by_name = {}
    for ev in trace:
        by_name.setdefault(ev["name"].split(":")[0], []).append(ev)
    submit = next(e for e in by_name["submit"]
                  if "parent_task" in e["name"])
    parent = by_name["parent_task"][0]
    kid = by_name["child"][0]
    tid = submit["args"]["trace_id"]
    assert tid and parent["args"]["trace_id"] == tid
    assert kid["args"]["trace_id"] == tid
    # tree: submit -> parent exec -> child exec
    assert parent["args"]["parent_id"] == submit["args"]["span_id"]
    assert kid["args"]["parent_id"] == parent["args"]["span_id"]


def test_profile_endpoint(ray_tpu_start):
    """/api/profile samples all control-plane threads on demand (ref:
    dashboard reporter profile_manager.py)."""
    import urllib.request

    from ray_tpu import dashboard

    port = dashboard.start_dashboard(port=0)
    try:
        @ray_tpu.remote
        def spin(n):
            return sum(range(n))

        refs = [spin.remote(200_000) for _ in range(50)]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/api/profile?seconds=1&hz=50",
                timeout=60) as r:
            prof = json.loads(r.read())
        ray_tpu.get(refs, timeout=60)
        assert prof["samples"] > 10
        # Cluster-wide shape now: merged collapsed-stack counts keyed
        # node:<hex>;pid:<pid>(<kind>);<thread>;<frames...>.
        assert prof["counts"], "no stacks sampled"
        assert prof["nodes"] and prof["errors"] == {}
        # the node-manager loop thread must appear
        assert any(";ray_tpu-node-manager;" in k
                   for k in prof["counts"])
    finally:
        dashboard.stop_dashboard()


def test_dashboard_spa_ui(ray_tpu_start):
    """The single-page UI serves at / (tabs over the /api surface; ref
    analogue: dashboard/client/src/), the legacy page stays at /simple,
    and the nodes API carries the Available resources the overview's
    usage bars read."""
    import json as _json
    import urllib.request

    from ray_tpu import dashboard

    port = dashboard.start_dashboard(port=0)
    page = urllib.request.urlopen(
        f"http://127.0.0.1:{port}/", timeout=30).read().decode()
    for marker in ("viewOverview", "viewTasks", "viewActors",
                   "viewMetrics", "/api/profile"):
        assert marker in page
    simple = urllib.request.urlopen(
        f"http://127.0.0.1:{port}/simple", timeout=30).read().decode()
    assert "<html" in simple
    nodes = _json.loads(urllib.request.urlopen(
        f"http://127.0.0.1:{port}/api/nodes", timeout=30).read())
    assert nodes and "Available" in nodes[0] and "Resources" in nodes[0]


def test_timeline_otlp_export(ray_tpu_start, tmp_path):
    """OTLP/JSON trace export: valid resourceSpans structure, fixed-width
    hex ids, consistent parent links, and POST to a (fake) OTLP/HTTP
    collector (ref analogue: the reference's OTel tracing_helper)."""
    import http.server
    import threading
    import urllib.request  # noqa: F401

    @ray_tpu.remote
    def outer():
        return ray_tpu.get(inner.remote())

    @ray_tpu.remote
    def inner():
        time.sleep(0.02)
        return "leaf"

    assert ray_tpu.get(outer.remote(), timeout=60) == "leaf"
    time.sleep(0.5)  # span buffers flush on a short timer

    got = {}

    class Collector(http.server.BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_POST(self):
            n = int(self.headers.get("Content-Length") or 0)
            got["body"] = json.loads(self.rfile.read(n))
            self.send_response(200)
            self.send_header("Content-Length", "2")
            self.end_headers()
            self.wfile.write(b"{}")

    srv = http.server.HTTPServer(("127.0.0.1", 0), Collector)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    out = str(tmp_path / "trace.otlp.json")
    payload = ray_tpu.timeline_otlp(
        endpoint=f"http://127.0.0.1:{srv.server_address[1]}/v1/traces",
        filename=out,
    )
    srv.shutdown()
    assert got["body"] == payload
    rs = payload["resourceSpans"][0]
    attrs = {a["key"]: a["value"]["stringValue"]
             for a in rs["resource"]["attributes"]}
    assert attrs["service.name"] == "ray_tpu"
    spans = rs["scopeSpans"][0]["spans"]
    assert spans, "no spans exported"
    for s in spans:
        assert len(s["traceId"]) == 32 and len(s["spanId"]) == 16
        assert int(s["endTimeUnixNano"]) >= int(s["startTimeUnixNano"])
    # the nested call produced a parent link within one trace
    by_trace = {}
    for s in spans:
        by_trace.setdefault(s["traceId"], []).append(s)
    assert any(
        any("parentSpanId" in s for s in group)
        for group in by_trace.values() if len(group) > 1
    ), "no parent-linked span tree in the export"
    import os
    assert os.path.exists(out)


def test_dashboard_agents_and_proxy(ray_tpu_start):
    """Per-node dashboard agents register in the KV; the head
    dashboard lists them and proxies logs/stats/profile requests (ref:
    dashboard/agent.py + the head's agent fan-out)."""
    import urllib.request

    import ray_tpu
    from ray_tpu import dashboard
    from ray_tpu.dashboard_agent import agent_addresses

    @ray_tpu.remote
    def noisy():
        print("agent-log-probe")
        return 1

    assert ray_tpu.get(noisy.remote()) == 1
    agents = agent_addresses()
    assert agents, "no dashboard agents registered"
    node_hex = next(iter(agents))

    port = dashboard.start_dashboard(port=0)
    try:
        def fetch(path):
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{path}", timeout=35) as r:
                return json.loads(r.read())

        assert fetch("/api/agents") == agents
        stats = fetch(f"/api/agent/{node_hex}/stats")
        assert stats["node_id"] == node_hex
        assert stats.get("rss_bytes", 0) > 0

        logs = fetch(f"/api/agent/{node_hex}/logs")
        worker_logs = [f["name"] for f in logs["files"]
                       if f["name"].startswith("worker-")]
        assert worker_logs, logs
        found = False
        for name in worker_logs:
            content = fetch(
                f"/api/agent/{node_hex}/logs/{name}?tail=50"
            )
            if any("agent-log-probe" in ln
                   for ln in content["lines"]):
                found = True
                break
        assert found, "probe line not found in worker logs"

        prof = fetch(
            f"/api/agent/{node_hex}/profile?seconds=0.3&hz=50"
        )
        assert prof["samples"] > 0 and prof["counts"]
    finally:
        dashboard.stop_dashboard()


def test_memory_state_refcounts(ray_tpu_start):
    """Object state rows carry live refcounts (the `rtpu memory`
    data; ref: `ray memory`)."""
    import numpy as np

    import ray_tpu
    from ray_tpu.util import state as state_api

    ref = ray_tpu.put(np.zeros(4096))
    # Driver-local refs reach the directory through the coalesced
    # ref-delta flusher; poll briefly.
    mine = []
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        rows = state_api.list_objects()
        mine = [r for r in rows if r["object_id"] == ref.hex()]
        if mine and mine[0]["refcount"] >= 1:
            break
        time.sleep(0.2)
    assert mine and mine[0]["refcount"] >= 1, mine
    assert mine[0]["size_bytes"] > 0
    assert all("refcount" in r for r in rows)


def test_direct_done_batch_coalescing(ray_tpu_start):
    """Direct actor-call completion notifications (worker -> NM) are
    debounced: a pipelined burst of direct calls must reach the node
    manager in direct_done_batch frames carrying MANY completions each,
    not one frame per call (the same coalescing discipline as
    task_done_batch on the NM-routed path)."""
    from ray_tpu.core.runtime_context import current_runtime

    @ray_tpu.remote
    class Echo:
        def ping(self, i):
            return i

    e = Echo.remote()
    rt = current_runtime()
    # Engage the direct channel (discovery flips ready once the NM-path
    # queue drains between calls).
    deadline = time.time() + 15
    while time.time() < deadline:
        ray_tpu.get(e.ping.remote(0))
        st = rt._direct_states.get(e.actor_id.binary())
        if st is not None and st["status"] == "ready":
            break
        time.sleep(0.02)
    assert st is not None and st["status"] == "ready", st
    nm = rt._nm
    base_items = nm._stats["direct_calls_done"]
    base_frames = nm._stats["direct_done_batches"]
    # Pipelined load: submit a burst, then resolve — the worker chews
    # through the whole batch and coalesces its notifications.
    for _ in range(3):
        assert ray_tpu.get(
            [e.ping.remote(i) for i in range(64)], timeout=60
        ) == list(range(64))
    deadline = time.time() + 10
    while time.time() < deadline:
        items = nm._stats["direct_calls_done"] - base_items
        if items >= 3 * 64:
            break
        time.sleep(0.1)
    items = nm._stats["direct_calls_done"] - base_items
    frames = nm._stats["direct_done_batches"] - base_frames
    assert items >= 3 * 64, (items, frames)
    # Coalescing under load: far fewer frames than completions.
    assert frames <= items // 4, (
        f"{frames} direct_done_batch frames for {items} completions — "
        "the worker->NM notification plane is not coalescing"
    )
