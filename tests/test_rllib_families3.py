"""RLlib family tests, batch 3: MADDPG, R2D2, AlphaZero."""

import sys as _sys

import cloudpickle as _cloudpickle
import numpy as np
import pytest

_cloudpickle.register_pickle_by_value(_sys.modules[__name__])


def _coop_push_env():
    """2-agent continuous cooperation: each agent sees its own target
    in [-1,1] and must output an action close to it; reward is shared
    and maximal only when BOTH match (so the centralized critic sees
    the joint effect)."""
    import numpy as _np

    class CoopPush:
        action_low = -_np.ones(1, _np.float32)
        action_high = _np.ones(1, _np.float32)

        def __init__(self):
            self._rng = _np.random.RandomState(0)
            self._t = 0

        def _obs(self):
            self._targets = self._rng.uniform(-0.8, 0.8, 2)
            return {f"a{i}": _np.asarray([self._targets[i]], "float32")
                    for i in range(2)}

        def reset(self, seed=None):
            if seed is not None:
                self._rng = _np.random.RandomState(seed)
            self._t = 0
            return self._obs(), {}

        def step(self, actions):
            errs = [abs(float(actions[f"a{i}"][0]) - self._targets[i])
                    for i in range(2)]
            team = -(errs[0] + errs[1])
            rew = {f"a{i}": team / 2.0 for i in range(2)}
            self._t += 1
            done = self._t >= 25
            return (self._obs(), rew, {"__all__": done},
                    {"__all__": False}, {})

    return CoopPush()


@pytest.mark.slow
def test_maddpg_learns_cooperative_control(ray_tpu_start):
    """MADDPG: centralized critics + decentralized actors drive the
    shared reward toward 0 (ref: rllib/algorithms/maddpg)."""
    from ray_tpu.rllib import MADDPGConfig

    config = (
        MADDPGConfig()
        .environment(_coop_push_env)
        .env_runners(num_env_runners=2, rollout_fragment_length=100)
        .training(lr=3e-3, minibatch_size=128,
                  num_updates_per_iteration=48,
                  num_steps_sampled_before_learning_starts=200,
                  act_dim=1, exploration_noise=0.3)
    )
    algo = config.build()
    try:
        first = algo.train()
        last = {}
        for _ in range(14):
            last = algo.train()
        assert last["num_learner_updates"] > 0
        assert np.isfinite(last["critic_loss"])
        # Random play: E[-2|u-t|]*25/... team reward per episode about
        # -2*0.73*25/2 per agent... just require clear improvement.
        assert last["episode_reward_mean"] > \
            first["episode_reward_mean"] + 5, (first, last)
    finally:
        algo.stop()


def _memory_env():
    """POMDP: the cue (+1/-1) is visible ONLY at t=0; afterwards obs is
    0. Every step rewards the action that matches the cue — solvable
    only by remembering it (LSTM), feedforward nets stay at chance."""
    import numpy as _np

    class _Box:
        def __init__(self, shape):
            self.shape = shape

    class _Disc:
        n = 2
        shape = ()

    class Memory:
        def __init__(self):
            self.observation_space = _Box((1,))
            self.action_space = _Disc()
            self._rng = _np.random.RandomState(0)
            self._t = 0

        def reset(self, seed=None):
            if seed is not None:
                self._rng = _np.random.RandomState(seed)
            self._t = 0
            self._cue = float(self._rng.choice([-1.0, 1.0]))
            return _np.asarray([self._cue], "float32"), {}

        def step(self, action):
            want = 1 if self._cue > 0 else 0
            r = 1.0 if int(action) == want else -1.0
            self._t += 1
            done = self._t >= 8
            obs = _np.asarray([0.0], "float32")  # cue hidden now
            return obs, r, False, done, {}

    return Memory()


@pytest.mark.slow
def test_r2d2_learns_memory_task(ray_tpu_start):
    """R2D2's LSTM + stored-state sequence replay solves a task that
    requires memory (ref: rllib/algorithms/r2d2)."""
    from ray_tpu.rllib import R2D2Config

    config = (
        R2D2Config()
        .environment(_memory_env)
        .env_runners(num_env_runners=2, rollout_fragment_length=96)
        .training(lr=3e-3, num_updates_per_iteration=24,
                  num_steps_sampled_before_learning_starts=300,
                  epsilon_timesteps=2500, seq_len=8,
                  target_network_update_freq=400)
        .debugging(seed=0)
    )
    algo = config.build()
    try:
        best = -9.0
        for _ in range(30):
            result = algo.train()
            if result["episodes_total"] > 0:
                best = max(best, result["episode_reward_mean"])
            if best > 5.5:
                break
        # Max 8 (first step sees the cue); chance ~0. A memoryless
        # policy cannot beat ~1 (first-step only).
        assert best > 5.5, best
    finally:
        algo.stop()


@pytest.mark.slow
def test_alpha_zero_tictactoe(ray_tpu_start):
    """AlphaZero self-play on TicTacToe: losses fall, the RAW policy
    (no search) learns sensible openings, and MCTS play never loses to
    a random opponent (ref: rllib/algorithms/alpha_zero)."""
    from ray_tpu.rllib import AlphaZeroConfig, TicTacToe

    config = (
        AlphaZeroConfig()
        .env_runners(num_env_runners=2)
        .training(lr=3e-3, minibatch_size=128)
        .debugging(seed=0)
    )
    config.num_simulations = 32
    config.games_per_iteration = 10
    config.train_batches_per_iteration = 12
    algo = config.build()
    try:
        first = algo.train()
        last = {}
        for _ in range(8):
            last = algo.train()
        assert last["num_positions"] > first["new_positions"]
        assert last["total_loss"] < first["total_loss"], (first, last)

        # MCTS-backed play vs a random opponent: never lose over 20
        # games as first player (tic-tac-toe is a draw at worst).
        game = TicTacToe()
        rng = np.random.RandomState(1)
        losses = 0
        for _ in range(20):
            s = game.initial_state()
            to_move_is_algo = True
            while True:
                term = game.terminal_value(s)
                if term is not None:
                    # term is for the player to move; the algo LOST if
                    # it is to move and the value is -1.
                    if term == -1.0 and to_move_is_algo:
                        losses += 1
                    break
                if to_move_is_algo:
                    a = algo.compute_action(s, use_mcts=True,
                                            num_simulations=48)
                else:
                    legal = game.legal_actions(s)
                    a = int(rng.choice(legal))
                s = game.next_state(s, a)
                to_move_is_algo = not to_move_is_algo
            assert losses == 0, f"lost {losses} games"
    finally:
        algo.stop()


@pytest.mark.slow
def test_decision_transformer_offline(ray_tpu_start):
    """DT conditioned on HIGH return imitates the good behavior in a
    mixed-quality offline dataset; conditioned evaluation beats the
    dataset average (ref: rllib/algorithms/dt)."""
    import ray_tpu.data as rd
    from ray_tpu.rllib import DTConfig

    # Episodes of length 6: obs = the signal; expert acts sign(obs)
    # (+1/step), anti-expert acts wrong (-1/step). Returns separate
    # the two behaviors cleanly.
    rng = np.random.RandomState(0)
    rows = []
    for ep in range(120):
        expert = ep % 2 == 0
        for t in range(6):
            sig = float(rng.choice([-1.0, 1.0]))
            want = 1 if sig > 0 else 0
            act = want if expert else 1 - want
            rows.append({
                "episode_id": ep, "t": t,
                "obs": np.asarray([sig], np.float32),
                "action": int(act),
                "reward": 1.0 if act == want else -1.0,
            })
    ds = rd.from_items(rows, override_num_blocks=4)
    algo = (
        DTConfig()
        .offline_data(ds)
        .training(lr=2e-3, minibatch_size=64, num_actions=2,
                  context_length=6)
        .debugging(seed=0)
        .build()
    )
    first = algo.train()
    last = {}
    for _ in range(14):
        last = algo.train()
    assert last["num_episodes"] == 120
    assert last["loss"] < first["loss"], (first, last)

    # Conditioned on the EXPERT return (+6), DT should pick the right
    # action for fresh signals.
    correct = 0
    trials = 40
    for i in range(trials):
        sig = 1.0 if i % 2 == 0 else -1.0
        a = algo.compute_action(
            {"obs": [np.asarray([sig], np.float32)], "actions": [],
             "rewards": []},
            target_return=6.0,
        )
        correct += int(a == (1 if sig > 0 else 0))
    assert correct / trials > 0.85, correct / trials


def test_algorithm_registry():
    """Name -> Config lookup with aliases (ref:
    rllib/algorithms/registry.py get_algorithm_class)."""
    from ray_tpu.rllib import get_algorithm_config, list_algorithms

    algos = list_algorithms()
    assert len(algos) >= 23, algos
    for name in list_algorithms() + ["APEX", "alpha-zero"]:
        cfg = get_algorithm_config(name)
        assert hasattr(cfg, "build")
    import pytest as _pytest

    with _pytest.raises(ValueError, match="unknown algorithm"):
        get_algorithm_config("dreamerv9")
