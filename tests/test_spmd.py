"""SPMD actor groups + TPU slice topology (SURVEY.md §7 phase 5 north star).

Covers: env-driven slice discovery, slice labels on nodes, label-selector
bundle placement, tpu_slice() gang reservation pinning rank i to slice
worker i, SpmdActorGroup lock-step semantics, and whole-group restart after
a member (or its node) dies — the consistent-restart contract a collective-
running gang requires."""

import time

import pytest

import ray_tpu
import ray_tpu.util
from ray_tpu.cluster_utils import Cluster
from ray_tpu.core import tpu
from ray_tpu.core.resources import ResourceSet
from ray_tpu.core.scheduling_policy import place_bundles
from ray_tpu.core.spmd import SpmdActorGroup, SpmdGroupError


# --------------------------------------------------------- discovery (pure)


def test_detect_slice_from_env(monkeypatch):
    monkeypatch.delenv("TPU_WORKER_HOSTNAMES", raising=False)
    monkeypatch.setenv("TPU_NAME", "pod-a")
    monkeypatch.setenv("TPU_WORKER_ID", "3")
    monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v5p-16")
    monkeypatch.setenv("TPU_TOPOLOGY", "2x2x2")
    monkeypatch.setenv("TPU_CHIPS_PER_HOST_OVERRIDE", "4")
    info = tpu.detect_slice()
    assert info is not None
    assert info.slice_name == "pod-a"
    assert info.worker_id == 3
    assert info.num_hosts == 2  # v5p-16 = 16 cores = 8 chips / 4 per host
    assert info.chips_per_host == 4
    labels = info.labels()
    assert labels[tpu.TPU_SLICE_LABEL] == "pod-a"
    assert labels[tpu.TPU_WORKER_ID_LABEL] == "3"
    assert labels[tpu.TPU_HOSTS_LABEL] == "2"


def test_detect_slice_absent(monkeypatch):
    monkeypatch.delenv("TPU_NAME", raising=False)
    monkeypatch.delenv("RAY_TPU_SLICE_NAME", raising=False)
    assert tpu.detect_slice() is None
    assert tpu.node_tpu_labels() == {}


def test_slice_host_accounting():
    assert tpu.slice_num_hosts("v5p-16") == 2  # 16 cores = 8 chips / 4
    assert tpu.slice_num_hosts("v4-8") == 1  # 8 cores = 4 chips, one host
    assert tpu.slice_num_hosts("v3-32") == 4  # 32 cores = 16 chips / 4
    assert tpu.slice_num_hosts("v5e-8") == 1  # v5e suffix counts chips
    assert tpu.chips_per_host("v6e-256") == 8


# ------------------------------------------------- label-selector placement


def _node(nid, labels=None, cpu=4, tpu_chips=0):
    res = {"CPU": cpu}
    if tpu_chips:
        res["TPU"] = tpu_chips
    return {
        "node_id": nid,
        "state": "alive",
        "labels": labels or {},
        "resources_available": dict(res),
        "resources_total": dict(res),
    }


def test_place_bundles_label_selectors():
    nodes = [
        _node("aa", {tpu.TPU_WORKER_ID_LABEL: "0"}, tpu_chips=4),
        _node("bb", {tpu.TPU_WORKER_ID_LABEL: "1"}, tpu_chips=4),
    ]
    bundles = [ResourceSet({"TPU": 4}), ResourceSet({"TPU": 4})]
    selectors = [
        {tpu.TPU_WORKER_ID_LABEL: "1"},
        {tpu.TPU_WORKER_ID_LABEL: "0"},
    ]
    # Selectors invert the default deterministic order.
    assert place_bundles(
        bundles, "STRICT_SPREAD", nodes, label_selectors=selectors
    ) == ["bb", "aa"]
    # Unsatisfiable selector -> unplaceable.
    assert (
        place_bundles(
            bundles,
            "STRICT_SPREAD",
            nodes,
            label_selectors=[{tpu.TPU_WORKER_ID_LABEL: "9"}] * 2,
        )
        is None
    )


# ------------------------------------------------------------ cluster tests


def _slice_labels(name, worker_id, hosts, accel="v5p-16"):
    return {
        tpu.TPU_SLICE_LABEL: name,
        tpu.TPU_WORKER_ID_LABEL: str(worker_id),
        tpu.TPU_TYPE_LABEL: accel,
        tpu.TPU_TOPOLOGY_LABEL: "2x2x2",
        tpu.TPU_HOSTS_LABEL: str(hosts),
    }


@pytest.fixture
def slice_cluster():
    c = Cluster(
        head_resources={"CPU": 2},
        system_config={"num_prestart_workers": 1, "default_max_retries": 0},
    )
    for wid in range(2):
        c.add_node(
            num_cpus=2,
            resources={"TPU": 4},
            labels=_slice_labels("pod-test", wid, hosts=2),
        )
    yield c
    c.shutdown()


def _make_rank_probe():
    """Defined per-test (classes in test modules aren't importable by
    workers until runtime_env working_dir ships; the same pattern the other
    cluster tests use)."""

    class _RankProbe:
        def __init__(self, rank=0):
            self.rank = rank

        def whoami(self):
            import ray_tpu as rt

            ctx = rt.get_runtime_context()
            return {"rank": self.rank, "node_id": ctx.get_node_id()}

        def echo(self, x):
            return x

    return _RankProbe


def test_tpu_slice_pins_ranks_to_workers(slice_cluster):
    _RankProbe = _make_rank_probe()
    pg = tpu.tpu_slice("pod-test")
    assert pg.bundle_count == 2
    table = ray_tpu.util.placement_group_table()[pg.id]
    chosen = table["nodes"]
    # Bundle i must sit on the node labelled worker-id i.
    views = {v["NodeID"]: v for v in ray_tpu.nodes()}
    for i, node_hex in enumerate(chosen):
        assert (
            views[node_hex]["Labels"][tpu.TPU_WORKER_ID_LABEL] == str(i)
        )
    group = SpmdActorGroup(
        _RankProbe,
        placement_group=pg,
        per_worker_args=lambda rank: ((rank,), {}),
    )
    out = group.run("whoami", timeout=30)
    assert [o["rank"] for o in out] == [0, 1]
    # Lock-step ranks landed on distinct slice hosts in worker order.
    assert [o["node_id"] for o in out] == list(chosen)
    group.shutdown()


def test_tpu_slice_autoselect_and_errors(slice_cluster):
    pg = tpu.tpu_slice()  # only one slice registered -> picked
    assert pg.bundle_count == 2
    ray_tpu.util.remove_placement_group(pg)
    with pytest.raises(ValueError):
        tpu.tpu_slice("no-such-slice")


def test_spmd_group_gang_and_lockstep(ray_tpu_start):
    _RankProbe = _make_rank_probe()
    group = SpmdActorGroup(
        _RankProbe,
        num_workers=2,
        resources_per_worker={"CPU": 1},
        per_worker_args=lambda rank: ((rank,), {}),
    )
    group.wait_ready(timeout=30)
    assert group.healthy()
    out = group.run("whoami", timeout=30)
    assert sorted(o["rank"] for o in out) == [0, 1]
    echoed = group.run("echo", 42, timeout=30)
    assert echoed == [42, 42]
    group.shutdown()
    assert group.broken


def test_spmd_group_infeasible_gang_fails_fast(ray_tpu_start):
    _RankProbe = _make_rank_probe()
    with pytest.raises(SpmdGroupError):
        SpmdActorGroup(
            _RankProbe,
            num_workers=2,
            resources_per_worker={"CPU": 64},
            ready_timeout=1.5,
        )


def test_spmd_group_member_death_breaks_group(ray_tpu_start):
    _RankProbe = _make_rank_probe()
    group = SpmdActorGroup(
        _RankProbe,
        num_workers=2,
        resources_per_worker={"CPU": 1},
        per_worker_args=lambda rank: ((rank,), {}),
    )
    group.wait_ready(timeout=30)
    ray_tpu.kill(group.actors[1])
    with pytest.raises(SpmdGroupError):
        group.run("whoami", timeout=30)
    assert group.broken
    with pytest.raises(SpmdGroupError):
        group.submit("whoami")
    # Whole-group restart brings back a full healthy gang.
    group.restart()
    out = group.run("whoami", timeout=30)
    assert sorted(o["rank"] for o in out) == [0, 1]
    group.shutdown()


def test_spmd_group_survives_node_death_with_replacement(slice_cluster):
    """Kill a slice host mid-run; after a replacement host with the same
    worker-id label joins, whole-group restart restores the gang (the
    gang-restart contract from VERDICT item 1)."""
    _RankProbe = _make_rank_probe()
    pg = tpu.tpu_slice("pod-test")
    group = SpmdActorGroup(
        _RankProbe,
        placement_group=pg,
        per_worker_args=lambda rank: ((rank,), {}),
    )
    group.wait_ready(timeout=30)

    victim = slice_cluster._nodes[-1]
    slice_cluster.remove_node(victim)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if not group.healthy(timeout=5):
            break
    assert group.broken

    # Replacement host registers with the dead worker's slice identity.
    slice_cluster.add_node(
        num_cpus=2,
        resources={"TPU": 4},
        labels=_slice_labels("pod-test", 1, hosts=2),
    )
    group.restart(ready_timeout=60)
    out = group.run("whoami", timeout=30)
    assert sorted(o["rank"] for o in out) == [0, 1]
    group.shutdown()
