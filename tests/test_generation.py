"""KV-cache decode correctness: cached generation must match the naive
full-recompute argmax loop exactly (greedy)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from ray_tpu.models import LlamaConfig, forward, init_params  # noqa: E402
from ray_tpu.models.generation import (  # noqa: E402
    KVCache,
    forward_with_cache,
    generate,
)


def _naive_greedy(params, prompt, cfg, n):
    seq = prompt
    out = []
    for _ in range(n):
        logits, _ = forward(params, seq, cfg)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        out.append(nxt)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    return jnp.stack(out, axis=1)


def test_cached_prefill_matches_forward():
    cfg = LlamaConfig.tiny()
    params = init_params(cfg, jax.random.PRNGKey(0))
    prompt = jnp.asarray(np.random.RandomState(0).randint(0, 256, (2, 8)))
    full_logits, _ = forward(params, prompt, cfg)
    cache = KVCache.create(cfg, 2, 32)
    cached_logits, cache = forward_with_cache(params, prompt, cache, cfg)
    np.testing.assert_allclose(
        np.asarray(cached_logits), np.asarray(full_logits[:, -1]),
        atol=1e-4, rtol=1e-4,
    )
    assert list(np.asarray(cache.lengths)) == [8, 8]


def test_generate_matches_naive():
    cfg = LlamaConfig.tiny()
    params = init_params(cfg, jax.random.PRNGKey(0))
    prompt = jnp.asarray(np.random.RandomState(1).randint(0, 256, (2, 6)))
    expected = _naive_greedy(params, prompt, cfg, 5)
    got = generate(params, prompt, cfg, max_new_tokens=5)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(expected))


def test_decode_respects_active_mask():
    cfg = LlamaConfig.tiny()
    params = init_params(cfg, jax.random.PRNGKey(0))
    cache = KVCache.create(cfg, 2, 16)
    prompt = jnp.asarray(np.random.RandomState(2).randint(0, 256, (2, 4)))
    _, cache = forward_with_cache(params, prompt, cache, cfg)
    tok = jnp.asarray([[5], [9]], dtype=jnp.int32)
    active = jnp.asarray([True, False])
    _, cache2 = forward_with_cache(params, tok, cache, cfg, active=active)
    assert list(np.asarray(cache2.lengths)) == [5, 4]
    # Inactive slot's cache rows untouched.
    np.testing.assert_array_equal(
        np.asarray(cache2.k[:, 1]), np.asarray(cache.k[:, 1])
    )
