"""KV-cache decode correctness: cached generation must match the naive
full-recompute argmax loop exactly (greedy)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from ray_tpu.models import LlamaConfig, forward, init_params  # noqa: E402
from ray_tpu.models.generation import (  # noqa: E402
    KVCache,
    forward_with_cache,
    generate,
)


def _naive_greedy(params, prompt, cfg, n):
    seq = prompt
    out = []
    for _ in range(n):
        logits, _ = forward(params, seq, cfg)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        out.append(nxt)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    return jnp.stack(out, axis=1)


def test_cached_prefill_matches_forward():
    cfg = LlamaConfig.tiny()
    params = init_params(cfg, jax.random.PRNGKey(0))
    prompt = jnp.asarray(np.random.RandomState(0).randint(0, 256, (2, 8)))
    full_logits, _ = forward(params, prompt, cfg)
    cache = KVCache.create(cfg, 2, 32)
    cached_logits, cache = forward_with_cache(params, prompt, cache, cfg)
    np.testing.assert_allclose(
        np.asarray(cached_logits), np.asarray(full_logits[:, -1]),
        atol=1e-4, rtol=1e-4,
    )
    assert list(np.asarray(cache.lengths)) == [8, 8]


@pytest.mark.slow
def test_generate_matches_naive():
    cfg = LlamaConfig.tiny()
    params = init_params(cfg, jax.random.PRNGKey(0))
    prompt = jnp.asarray(np.random.RandomState(1).randint(0, 256, (2, 6)))
    expected = _naive_greedy(params, prompt, cfg, 5)
    got = generate(params, prompt, cfg, max_new_tokens=5)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(expected))


def test_decode_respects_active_mask():
    cfg = LlamaConfig.tiny()
    params = init_params(cfg, jax.random.PRNGKey(0))
    cache = KVCache.create(cfg, 2, 16)
    prompt = jnp.asarray(np.random.RandomState(2).randint(0, 256, (2, 4)))
    _, cache = forward_with_cache(params, prompt, cache, cfg)
    tok = jnp.asarray([[5], [9]], dtype=jnp.int32)
    active = jnp.asarray([True, False])
    _, cache2 = forward_with_cache(params, tok, cache, cfg, active=active)
    assert list(np.asarray(cache2.lengths)) == [5, 4]
    # Inactive slot's cache rows untouched.
    np.testing.assert_array_equal(
        np.asarray(cache2.k[:, 1]), np.asarray(cache.k[:, 1])
    )


@pytest.mark.slow
def test_moe_cached_decode_matches_naive():
    """MoE (Mixtral-style) models decode through the KV cache (r1 gap:
    generation.py raised NotImplementedError for MoE)."""
    import dataclasses

    # capacity_factor high enough that no token is dropped: with drops,
    # full-sequence and incremental eval legitimately group tokens
    # differently and exact equality is not defined.
    cfg = dataclasses.replace(
        LlamaConfig.tiny(moe=True), capacity_factor=8.0
    )
    params = init_params(cfg, jax.random.PRNGKey(1))
    prompt = jnp.asarray(np.random.RandomState(1).randint(0, 256, (2, 6)))
    naive = _naive_greedy(params, prompt, cfg, 5)
    out = generate(params, prompt, cfg, max_new_tokens=5)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(naive))


def test_bucketed_prefill_matches_exact():
    """Padded (bucketed) prefill with last_index/append_len produces the
    same logits and cache lengths as exact-length prefill."""
    cfg = LlamaConfig.tiny()
    params = init_params(cfg, jax.random.PRNGKey(0))
    rs = np.random.RandomState(2)
    real_len = 5
    prompt = jnp.asarray(rs.randint(0, 256, (1, real_len)))
    exact_logits, exact_cache = forward_with_cache(
        params, prompt, KVCache.create(cfg, 1, 32), cfg
    )
    bucket = 8
    padded = jnp.concatenate(
        [prompt, jnp.zeros((1, bucket - real_len), jnp.int32)], axis=1
    )
    padded_logits, padded_cache = forward_with_cache(
        params, padded, KVCache.create(cfg, 1, 32), cfg,
        last_index=jnp.asarray([real_len - 1]),
        append_len=jnp.asarray(real_len),
    )
    np.testing.assert_allclose(
        np.asarray(padded_logits), np.asarray(exact_logits),
        atol=1e-4, rtol=1e-4,
    )
    assert int(padded_cache.lengths[0]) == real_len
    # Decode continues identically from either cache.
    nxt = jnp.argmax(exact_logits, -1).astype(jnp.int32)[:, None]
    l1, _ = forward_with_cache(params, nxt, exact_cache, cfg)
    l2, _ = forward_with_cache(params, nxt, padded_cache, cfg)
    np.testing.assert_allclose(np.asarray(l1), np.asarray(l2),
                               atol=1e-4, rtol=1e-4)


def test_paged_attention_kernel_matches_gather():
    """The Pallas page-walk decode kernel (ops/paged_attention.py)
    matches the XLA gather path bit-for-near: random page tables,
    lengths spanning page boundaries, GQA groups (VERDICT r3 ask #7)."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from ray_tpu.models.generation import _attend_paged_xla
    from ray_tpu.models.llama import LlamaConfig
    from ray_tpu.ops.paged_attention import paged_decode_attention

    B, H, Hkv, D = 3, 4, 2, 128
    L, P_total, page, Pmax = 2, 8, 16, 4
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, 1, H, D), jnp.float32)
    ck = jnp.asarray(rng.randn(L, Hkv, P_total, page, D), jnp.float32)
    cv = jnp.asarray(rng.randn(L, Hkv, P_total, page, D), jnp.float32)
    # distinct pages per slot, deliberately out of order
    page_table = jnp.asarray(
        [[3, 1, 6, 0], [2, 5, 7, 4], [0, 6, 1, 3]], jnp.int32)
    lengths = jnp.asarray([0, 17, 63], jnp.int32)  # cell 0 / mid / last

    cfg = LlamaConfig.tiny()
    for layer in range(L):
        ref = _attend_paged_xla(q, ck[layer], cv[layer], page_table,
                                lengths, cfg)
        out = paged_decode_attention(
            q[:, 0], ck[layer], cv[layer], page_table, lengths,
            interpret=True,
        )[:, None]
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)
        # and the full-pool form with a static layer baked into the
        # kernel's index map
        out2 = paged_decode_attention(
            q[:, 0], ck, cv, page_table, lengths, layer=layer,
            interpret=True,
        )[:, None]
        np.testing.assert_allclose(np.asarray(out2), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)
