"""Test configuration.

JAX tests run on a virtual 8-device CPU mesh (multi-chip sharding is
validated without TPU hardware, per the reference's pattern of testing
multi-node semantics on one machine — SURVEY.md §4). These env vars must be
set before jax is imported anywhere in the test process.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pytest


@pytest.fixture
def ray_tpu_start():
    """Boot a real single-node runtime per test (ref analogue: the
    ray_start_regular fixture, python/ray/tests/conftest.py:411)."""
    import ray_tpu

    rt = ray_tpu.init(
        num_cpus=4,
        system_config={
            "num_prestart_workers": 2,
            "refcount_flush_interval_s": 0.1,
            "gc_grace_period_s": 1.0,
        },
    )
    yield rt
    ray_tpu.shutdown()
