"""Test configuration.

JAX tests run on a virtual 8-device CPU mesh (multi-chip sharding is
validated without TPU hardware, per the reference's pattern of testing
multi-node semantics on one machine — SURVEY.md §4). These env vars must be
set before jax is imported anywhere in the test process.
"""

import os
import sys

# The axon TPU hook (sitecustomize) imports jax at interpreter start when
# PALLAS_AXON_POOL_IPS is set, which locks the backend before
# xla_force_host_platform_device_count can apply. pytest_configure below
# re-execs pytest once with a cleaned environment so tests get the virtual
# 8-device CPU mesh (after suspending pytest's fd capture, which would
# otherwise swallow the re-exec'd process's output).


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long multi-node chaos/drain tests (tier-1 runs -m 'not "
        "slow'; `make chaos` runs them)",
    )
    if os.environ.get("PALLAS_AXON_POOL_IPS") and not os.environ.get(
        "RAY_TPU_TEST_REEXEC"
    ):
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["RAY_TPU_TEST_REEXEC"] = "1"
        env["JAX_PLATFORMS"] = "cpu"
        flags = env.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            env["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
        capman = config.pluginmanager.getplugin("capturemanager")
        if capman is not None:
            capman.stop_global_capturing()
        os.execve(
            sys.executable, [sys.executable, "-m", "pytest"] + sys.argv[1:], env
        )


os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pytest


@pytest.fixture
def ray_tpu_start():
    """Boot a real single-node runtime per test (ref analogue: the
    ray_start_regular fixture, python/ray/tests/conftest.py:411)."""
    import ray_tpu

    rt = ray_tpu.init(
        num_cpus=4,
        system_config={
            "num_prestart_workers": 2,
            "refcount_flush_interval_s": 0.1,
            "gc_grace_period_s": 1.0,
        },
    )
    yield rt
    ray_tpu.shutdown()
