"""Head (GCS) restart under a LIVE cluster (VERDICT r2 ask #5; ref
analogue: NotifyGCSRestart, node_manager.proto:361 +
gcs_rpc_server_reconnect_timeout_s, ray_config_def.h:451).

Topology: head runs as a SEPARATE subprocess (rtpu start --head --block)
so it can be killed alone; one worker node subprocess carries a named
actor; drivers attach by GCS address. Kill ONLY the head, restart it on
the same port from its snapshot, and assert the surviving worker node
re-attaches and its named actor is callable again."""

import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import time
import uuid

import pytest

import ray_tpu


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn_head(port: int, storage: str) -> subprocess.Popen:
    env = dict(os.environ)
    env["RAY_TPU_GCS_STORAGE_PATH"] = storage
    env["RAY_TPU_HEARTBEAT_INTERVAL_S"] = "0.1"
    env.pop("RAY_TPU_ADDRESS", None)
    return subprocess.Popen(
        [sys.executable, "-m", "ray_tpu.scripts.cli", "start", "--head",
         "--block", "--port", str(port), "--num-cpus", "1"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
    )


def _spawn_worker(port: int) -> subprocess.Popen:
    env = dict(os.environ)
    env["RAY_TPU_GCS_ADDRESS"] = f"127.0.0.1:{port}"
    env["RAY_TPU_SESSION_DIR"] = os.path.join(
        tempfile.gettempdir(), "ray_tpu",
        f"hr-worker-{uuid.uuid4().hex[:8]}",
    )
    env["RAY_TPU_RESOURCES"] = json.dumps({"CPU": 2, "gadget": 1})
    env["RAY_TPU_HEARTBEAT_INTERVAL_S"] = "0.1"
    env["RAY_TPU_GCS_RECONNECT_TIMEOUT_S"] = "60"
    return subprocess.Popen(
        [sys.executable, "-m", "ray_tpu.core.node_main"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
    )


def _wait_gcs(port: int, timeout: float = 60.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            s = socket.create_connection(("127.0.0.1", port), timeout=1)
            s.close()
            return
        except OSError:
            time.sleep(0.2)
    raise TimeoutError(f"GCS on port {port} never came up")


def test_head_restart_with_live_worker(tmp_path):
    storage = str(tmp_path / "gcs.snapshot")
    port = _free_port()
    head = _spawn_head(port, storage)
    worker = None
    try:
        _wait_gcs(port)
        worker = _spawn_worker(port)

        # Driver 1: create a named actor ON THE WORKER node.
        ray_tpu.init(address=f"127.0.0.1:{port}")
        try:
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                if "gadget" in ray_tpu.cluster_resources():
                    break
                time.sleep(0.3)
            assert "gadget" in ray_tpu.cluster_resources(), \
                "worker node never registered"

            @ray_tpu.remote(resources={"gadget": 1})
            class Survivor:
                def __init__(self):
                    self.calls = 0

                def bump(self):
                    self.calls += 1
                    return self.calls

            a = Survivor.options(name="survivor").remote()
            assert ray_tpu.get(a.bump.remote(), timeout=120) == 1
            # Snapshot must contain the named actor before the kill.
            deadline = time.monotonic() + 20
            while time.monotonic() < deadline and \
                    not os.path.exists(storage):
                time.sleep(0.2)
            assert os.path.exists(storage)
            time.sleep(1.0)  # one more snapshot interval for good measure
        finally:
            ray_tpu.shutdown()

        # Kill ONLY the head. The worker stays up.
        head.send_signal(signal.SIGKILL)
        head.wait(timeout=30)
        time.sleep(1.0)
        assert worker.poll() is None, "worker died with the head"

        # Restart the head on the same port from the snapshot.
        head = _spawn_head(port, storage)
        _wait_gcs(port)

        # Worker must re-register within its reconnect window.
        ray_tpu.init(address=f"127.0.0.1:{port}")
        try:
            deadline = time.monotonic() + 90
            ok = False
            while time.monotonic() < deadline:
                views = [v for v in ray_tpu.nodes() if v.get("Alive")]
                if any("gadget" in (v.get("Resources") or {})
                       for v in views):
                    ok = True
                    break
                time.sleep(0.5)
            assert ok, "worker node never re-registered after head restart"
            assert worker.poll() is None, "worker exited during reconnect"

            # The named actor on the surviving node is callable again —
            # with its STATE intact (calls continues from 1).
            deadline = time.monotonic() + 60
            handle = None
            while time.monotonic() < deadline:
                try:
                    handle = ray_tpu.get_actor("survivor")
                    break
                except Exception:
                    time.sleep(0.5)
            assert handle is not None, "named actor not recovered"
            assert ray_tpu.get(handle.bump.remote(), timeout=120) == 2
        finally:
            ray_tpu.shutdown()
    finally:
        for proc in (worker, head):
            if proc is not None and proc.poll() is None:
                proc.send_signal(signal.SIGKILL)
                try:
                    proc.wait(timeout=10)
                except Exception:
                    pass
