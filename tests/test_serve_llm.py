"""Continuous-batching LLM engine tests."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from ray_tpu.models import LlamaConfig, init_params  # noqa: E402
from ray_tpu.models.generation import generate  # noqa: E402
from ray_tpu.serve.llm import LLMEngine  # noqa: E402


@pytest.fixture(scope="module")
def tiny_model():
    cfg = LlamaConfig.tiny()
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def test_engine_single_request_matches_generate(tiny_model):
    cfg, params = tiny_model
    engine = LLMEngine(cfg, params, max_batch=4, max_len=64)
    try:
        prompt = list(np.random.RandomState(0).randint(0, 256, 6))
        expected = np.asarray(
            generate(params, jnp.asarray([prompt]), cfg, max_new_tokens=8)
        )[0].tolist()
        got = engine.generate(prompt, max_new_tokens=8)
        assert got == expected
    finally:
        engine.shutdown()


@pytest.mark.slow
def test_engine_concurrent_requests_continuous_batching(tiny_model):
    cfg, params = tiny_model
    engine = LLMEngine(cfg, params, max_batch=4, max_len=64)
    try:
        rng = np.random.RandomState(1)
        prompts = [list(rng.randint(0, 256, int(n))) for n in (4, 6, 5, 7)]
        lens = [10, 3, 7, 5]
        expected = [
            np.asarray(
                generate(params, jnp.asarray([p]), cfg, max_new_tokens=n)
            )[0].tolist()
            for p, n in zip(prompts, lens)
        ]
        # Submit all concurrently: they share the decode loop.
        reqs = [engine.submit(p, n) for p, n in zip(prompts, lens)]
        results = [r.result(timeout=120) for r in reqs]
        assert results == expected
        # Batched decode actually happened: fewer steps than total tokens.
        stats = engine.stats()
        assert stats["decode_steps"] < sum(lens)
    finally:
        engine.shutdown()


def test_engine_more_requests_than_slots(tiny_model):
    cfg, params = tiny_model
    engine = LLMEngine(cfg, params, max_batch=2, max_len=64)
    try:
        prompts = [[1, 2, 3], [4, 5], [6, 7, 8, 9], [10], [11, 12]]
        reqs = [engine.submit(p, 4) for p in prompts]
        results = [r.result(timeout=120) for r in reqs]
        assert all(len(r) == 4 for r in results)
    finally:
        engine.shutdown()


def test_engine_ttft_recorded(tiny_model):
    cfg, params = tiny_model
    engine = LLMEngine(cfg, params, max_batch=2, max_len=64)
    try:
        req = engine.submit([1, 2, 3, 4], 4)
        req.result(timeout=120)
        assert req.ttft_s is not None and req.ttft_s > 0
    finally:
        engine.shutdown()


@pytest.mark.slow
def test_llm_serve_deployment(ray_tpu_start):
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.serve.llm import LLMDeployment

    dep = serve.deployment(LLMDeployment).options(
        name="llm",
        ray_actor_options={"max_concurrency": 8, "num_cpus": 1},
    )
    handle = serve.run(dep.bind(max_batch=4, max_len=64))
    try:
        futs = [
            handle.remote({"prompt": [1, 2, 3 + i], "max_new_tokens": 5})
            for i in range(6)
        ]
        outs = [f.result(timeout=180) for f in futs]
        assert all(len(o["tokens"]) == 5 for o in outs)
        stats = serve.get_deployment_handle("llm").options(
            method="stats"
        ).remote().result(timeout=60)
        assert stats["decode_steps"] >= 1
    finally:
        serve.shutdown()


def test_paged_cache_page_reuse(tiny_model):
    """Pages recycle across requests: an oversubscribed pool (too small
    for all slots at max_len) still serves sequential waves, and the free
    count returns to total when idle."""
    cfg, params = tiny_model
    # 4 slots x max_len 64 would need 16 pages; give only 6 (page=16).
    engine = LLMEngine(cfg, params, max_batch=4, max_len=64,
                       page_size=16, total_pages=6)
    try:
        for wave in range(3):
            outs = [
                engine.submit([1, 2, 3 + wave + i], max_new_tokens=4)
                for i in range(4)
            ]
            for r in outs:
                assert len(r.result(timeout=180)) == 4
        stats = engine.stats()
        assert stats["free_pages"] == stats["total_pages"] == 6
        assert stats["active_slots"] == 0
    finally:
        engine.shutdown()


def test_paged_admission_waits_for_pages(tiny_model):
    """A request that cannot reserve pages queues until a running one
    releases them (admission control instead of OOM)."""
    cfg, params = tiny_model
    # One page per request wave: prompt+max_new <= 16 -> 1 page each, but
    # give the pool only 1 page total so requests serialize.
    engine = LLMEngine(cfg, params, max_batch=2, max_len=32,
                       page_size=16, total_pages=1)
    try:
        a = engine.submit([1, 2, 3], max_new_tokens=4)
        b = engine.submit([4, 5, 6], max_new_tokens=4)
        assert len(a.result(timeout=180)) == 4
        assert len(b.result(timeout=180)) == 4
        assert engine.stats()["free_pages"] == 1
    finally:
        engine.shutdown()


def test_engine_token_streaming(tiny_model):
    """req.tokens() yields tokens incrementally and matches the final
    output list."""
    cfg, params = tiny_model
    engine = LLMEngine(cfg, params, max_batch=2, max_len=64)
    try:
        req = engine.submit([7, 8, 9], max_new_tokens=6)
        streamed = list(req.tokens(timeout=120))
        assert streamed == req.result(timeout=1)
        assert len(streamed) == 6
    finally:
        engine.shutdown()


@pytest.mark.slow
def test_llm_serve_sse_streaming(ray_tpu_start):
    """End-to-end: HTTP proxy streams SSE tokens from the LLM decode loop
    as they are generated (VERDICT r2 ask #4)."""
    import json as _json
    import urllib.request

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.serve.http_proxy import start_proxy, stop_proxy
    from ray_tpu.serve.llm import LLMDeployment

    dep = serve.deployment(LLMDeployment).options(
        name="llmstream",
        ray_actor_options={"max_concurrency": 8, "num_cpus": 1},
    )
    serve.run(dep.bind(max_batch=2, max_len=64))
    port = start_proxy(0)
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/llmstream/stream",
            data=_json.dumps(
                {"prompt": [1, 2, 3], "max_new_tokens": 5}
            ).encode(),
            headers={"Content-Type": "application/json"},
        )
        tokens = []
        with urllib.request.urlopen(req, timeout=120) as r:
            assert r.headers.get("Content-Type") == "text/event-stream"
            for raw in r:
                line = raw.decode().strip()
                if line.startswith("data:"):
                    payload = _json.loads(line[5:].strip())
                    if payload is not None and "token" in payload:
                        tokens.append(payload["token"])
        assert len(tokens) == 5
    finally:
        stop_proxy()
        serve.shutdown()
