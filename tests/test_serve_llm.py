"""Continuous-batching LLM engine tests."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from ray_tpu.models import LlamaConfig, init_params  # noqa: E402
from ray_tpu.models.generation import generate  # noqa: E402
from ray_tpu.serve.llm import LLMEngine  # noqa: E402


@pytest.fixture(scope="module")
def tiny_model():
    cfg = LlamaConfig.tiny()
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def test_engine_single_request_matches_generate(tiny_model):
    cfg, params = tiny_model
    engine = LLMEngine(cfg, params, max_batch=4, max_len=64)
    try:
        prompt = list(np.random.RandomState(0).randint(0, 256, 6))
        expected = np.asarray(
            generate(params, jnp.asarray([prompt]), cfg, max_new_tokens=8)
        )[0].tolist()
        got = engine.generate(prompt, max_new_tokens=8)
        assert got == expected
    finally:
        engine.shutdown()


def test_engine_concurrent_requests_continuous_batching(tiny_model):
    cfg, params = tiny_model
    engine = LLMEngine(cfg, params, max_batch=4, max_len=64)
    try:
        rng = np.random.RandomState(1)
        prompts = [list(rng.randint(0, 256, int(n))) for n in (4, 6, 5, 7)]
        lens = [10, 3, 7, 5]
        expected = [
            np.asarray(
                generate(params, jnp.asarray([p]), cfg, max_new_tokens=n)
            )[0].tolist()
            for p, n in zip(prompts, lens)
        ]
        # Submit all concurrently: they share the decode loop.
        reqs = [engine.submit(p, n) for p, n in zip(prompts, lens)]
        results = [r.result(timeout=120) for r in reqs]
        assert results == expected
        # Batched decode actually happened: fewer steps than total tokens.
        stats = engine.stats()
        assert stats["decode_steps"] < sum(lens)
    finally:
        engine.shutdown()


def test_engine_more_requests_than_slots(tiny_model):
    cfg, params = tiny_model
    engine = LLMEngine(cfg, params, max_batch=2, max_len=64)
    try:
        prompts = [[1, 2, 3], [4, 5], [6, 7, 8, 9], [10], [11, 12]]
        reqs = [engine.submit(p, 4) for p in prompts]
        results = [r.result(timeout=120) for r in reqs]
        assert all(len(r) == 4 for r in results)
    finally:
        engine.shutdown()


def test_engine_ttft_recorded(tiny_model):
    cfg, params = tiny_model
    engine = LLMEngine(cfg, params, max_batch=2, max_len=64)
    try:
        req = engine.submit([1, 2, 3, 4], 4)
        req.result(timeout=120)
        assert req.ttft_s is not None and req.ttft_s > 0
    finally:
        engine.shutdown()


def test_llm_serve_deployment(ray_tpu_start):
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.serve.llm import LLMDeployment

    dep = serve.deployment(LLMDeployment).options(
        name="llm",
        ray_actor_options={"max_concurrency": 8, "num_cpus": 1},
    )
    handle = serve.run(dep.bind(max_batch=4, max_len=64))
    try:
        futs = [
            handle.remote({"prompt": [1, 2, 3 + i], "max_new_tokens": 5})
            for i in range(6)
        ]
        outs = [f.result(timeout=180) for f in futs]
        assert all(len(o["tokens"]) == 5 for o in outs)
        stats = serve.get_deployment_handle("llm").options(
            method="stats"
        ).remote().result(timeout=60)
        assert stats["decode_steps"] >= 1
    finally:
        serve.shutdown()
