"""RLlib family tests, batch 2: dueling/n-step DQN, Ape-X, QMIX, CRR."""

import sys as _sys

import cloudpickle as _cloudpickle
import numpy as np
import pytest

# Env factories are module-level; workers cannot import this test
# module, so ship everything from it by value.
_cloudpickle.register_pickle_by_value(_sys.modules[__name__])


def _sign_env():
    """Discrete toy: obs=[signal in {-1,+1}]; action must match the
    sign (+1 reward, else -1); 30-step episodes."""
    import numpy as _np

    class _Box:
        def __init__(self, shape):
            self.shape = shape

    class _Disc:
        n = 2
        shape = ()

    class Sign:
        def __init__(self):
            self.observation_space = _Box((1,))
            self.action_space = _Disc()
            self._rng = _np.random.RandomState(0)
            self._t = 0

        def _obs(self):
            self._sig = float(self._rng.choice([-1.0, 1.0]))
            return _np.asarray([self._sig], "float32")

        def reset(self, seed=None):
            if seed is not None:
                self._rng = _np.random.RandomState(seed)
            self._t = 0
            return self._obs(), {}

        def step(self, action):
            want = 1 if self._sig > 0 else 0
            r = 1.0 if int(action) == want else -1.0
            self._t += 1
            return self._obs(), r, False, self._t >= 30, {}

    return Sign()


def test_nstep_returns_unit():
    """n-step folding: rewards accumulate with discount, bootstrap
    stops at episode ends, DISCOUNT carries gamma^k."""
    from ray_tpu.rllib.dqn import DISCOUNT, nstep_returns
    from ray_tpu.rllib.env_runner import NEXT_OBS
    from ray_tpu.rllib.sample_batch import (
        ACTIONS, DONES, OBS, REWARDS, SampleBatch,
    )

    obs = np.arange(5, dtype=np.float32)[:, None]
    nxt = obs + 1
    b = SampleBatch({
        OBS: obs, ACTIONS: np.zeros(5, np.int64),
        REWARDS: np.asarray([1, 1, 1, 1, 1], np.float32),
        DONES: np.asarray([False, False, True, False, False]),
        NEXT_OBS: nxt,
    })
    out = nstep_returns(b, 3, 0.5)
    # t=0: r0 + 0.5 r1 + 0.25 r2, done at t=2 -> discount 0.
    np.testing.assert_allclose(out[REWARDS][0], 1.75)
    assert out[DISCOUNT][0] == 0.0
    np.testing.assert_allclose(out[NEXT_OBS][0], nxt[2])
    # t=3: r3 + 0.5 r4 (fragment tail), bootstrap at gamma^2.
    np.testing.assert_allclose(out[REWARDS][3], 1.5)
    np.testing.assert_allclose(out[DISCOUNT][3], 0.25)
    np.testing.assert_allclose(out[NEXT_OBS][3], nxt[4])
    # n=1 reduces to the classic single-step shape.
    one = nstep_returns(b, 1, 0.9)
    np.testing.assert_allclose(one[REWARDS], b[REWARDS])
    np.testing.assert_allclose(
        one[DISCOUNT], [0.9, 0.9, 0.0, 0.9, 0.9]
    )

    # TRUNCATION at t=2 (boundary without done): the lookahead must not
    # cross into the next episode, but the bootstrap stays on.
    from ray_tpu.rllib.env_runner import BOUNDARY

    b[BOUNDARY] = np.asarray([False, False, True, False, False])
    b["dones"] = np.asarray([False] * 5)
    tr = nstep_returns(b, 3, 0.5)
    np.testing.assert_allclose(tr[REWARDS][1], 1.5)   # r1 + 0.5 r2
    np.testing.assert_allclose(tr[DISCOUNT][1], 0.25)  # bootstraps
    np.testing.assert_allclose(tr[NEXT_OBS][1], nxt[2])


@pytest.mark.slow
def test_dqn_dueling_nstep_learns(ray_tpu_start):
    """DQN with dueling heads + 3-step returns still learns the sign
    task (ref: the reference DQN's `dueling` and `n_step` options)."""
    from ray_tpu.rllib import DQNConfig

    config = (
        DQNConfig()
        .environment(_sign_env)
        .env_runners(num_env_runners=2, rollout_fragment_length=100)
        .training(lr=3e-3, minibatch_size=128,
                  num_updates_per_iteration=32,
                  num_steps_sampled_before_learning_starts=300,
                  epsilon_timesteps=2000, dueling=True, n_step=3)
    )
    algo = config.build()
    try:
        best = -31.0
        for _ in range(15):
            result = algo.train()
            if result["episodes_total"] > 0:
                best = max(best, result["episode_reward_mean"])
            if best > 24:
                break
        assert best > 24, best
    finally:
        algo.stop()


@pytest.mark.slow
def test_apex_dqn_learns(ray_tpu_start):
    """Ape-X: replay actor + epsilon ladder + async rollouts learn the
    sign task (ref: rllib/algorithms/apex_dqn)."""
    from ray_tpu.rllib import ApexDQNConfig

    config = (
        ApexDQNConfig()
        .environment(_sign_env)
        .env_runners(num_env_runners=3, rollout_fragment_length=100)
        .training(lr=3e-3, minibatch_size=128,
                  num_updates_per_iteration=48,
                  num_steps_sampled_before_learning_starts=300,
                  target_network_update_freq=400)
    )
    algo = config.build()
    try:
        # Ladder: first runner most exploratory, last greediest.
        assert algo._ladder[0] > algo._ladder[-1]
        best = -31.0
        for _ in range(25):
            result = algo.train()
            if result["episodes_total"] > 0:
                best = max(best, result["episode_reward_mean"])
            if best > 20:
                break
        # The most exploratory runners keep ~40% random actions, so the
        # mean across runners saturates below the greedy optimum.
        assert best > 20, best
        assert result["buffer_size"] > 0
    finally:
        algo.stop()


def _coop_env():
    """2-agent cooperative sign task with a JOINT bonus: each agent
    sees its own signal; the team reward pays +1 per correct agent and
    an extra +1 only when BOTH are correct (value factorization helps)."""
    import numpy as _np

    class Coop:
        def __init__(self):
            self._rng = _np.random.RandomState(0)
            self._t = 0

        def _obs(self):
            self._sig = self._rng.choice([-1.0, 1.0], size=2)
            return {f"a{i}": _np.asarray([self._sig[i]], "float32")
                    for i in range(2)}

        def reset(self, seed=None):
            if seed is not None:
                self._rng = _np.random.RandomState(seed)
            self._t = 0
            return self._obs(), {}

        def step(self, actions):
            correct = [
                int(actions[f"a{i}"]) == (1 if self._sig[i] > 0 else 0)
                for i in range(2)
            ]
            team = float(sum(correct)) + (1.0 if all(correct) else 0.0)
            rew = {f"a{i}": team / 2.0 for i in range(2)}
            self._t += 1
            done = self._t >= 25
            return (self._obs(), rew, {"__all__": done},
                    {"__all__": False}, {})

    return Coop()


@pytest.mark.slow
def test_qmix_learns_cooperative_task(ray_tpu_start):
    """QMIX: shared utility net + monotonic mixer solves the
    cooperative sign task (ref: rllib/algorithms/qmix)."""
    from ray_tpu.rllib import QMIXConfig

    config = (
        QMIXConfig()
        .environment(_coop_env)
        .env_runners(num_env_runners=2, rollout_fragment_length=100)
        .training(lr=3e-3, minibatch_size=128,
                  num_updates_per_iteration=32,
                  num_steps_sampled_before_learning_starts=300,
                  epsilon_timesteps=3000, num_actions=2)
    )
    algo = config.build()
    try:
        best = 0.0
        for _ in range(20):
            result = algo.train()
            if result["episodes_total"] > 0:
                best = max(best, result["episode_reward_mean"])
            if best > 60:
                break
        # Max team return = 25 steps * 3 = 75; random ~ 25*1.25/... ~31.
        assert best > 60, best
        assert np.isfinite(result["td_loss"])
    finally:
        algo.stop()


@pytest.mark.slow
def test_crr_offline_continuous(ray_tpu_start):
    """CRR: advantage-filtered regression distills a better-than-
    behavior policy from noisy logged data (ref:
    rllib/algorithms/crr)."""
    import ray_tpu.data as rd
    from ray_tpu.rllib import CRRConfig

    rng = np.random.RandomState(0)
    n = 4000
    obs = rng.uniform(-1, 1, (n, 1)).astype(np.float32)
    act = np.clip(-obs + 0.4 * rng.randn(n, 1), -1, 1).astype(np.float32)
    rew = (-np.abs(obs + act))[:, 0].astype(np.float32)
    next_obs = rng.uniform(-1, 1, (n, 1)).astype(np.float32)
    ds = rd.from_items(
        [{"obs": obs[i], "action": act[i], "reward": float(rew[i]),
          "next_obs": next_obs[i], "done": 0.0} for i in range(n)],
        override_num_blocks=8,
    )
    algo = (
        CRRConfig()
        .offline_data(ds)
        .training(lr=3e-3, minibatch_size=256, gamma=0.5, beta=0.5)
        .build()
    )
    first = algo.train()
    last = {}
    for _ in range(6):
        last = algo.train()
    assert last["num_learner_updates"] > 0
    assert last["td_loss"] < first["td_loss"], (first, last)
    assert 0 < last["mean_weight"] < 20, last

    # The distilled actor should act close to a=-x on held-out states.
    import jax.numpy as jnp

    from ray_tpu.rllib.core import DeterministicActorModule

    w = algo.get_weights()
    test_obs = np.linspace(-0.9, 0.9, 21, dtype=np.float32)[:, None]
    a = np.asarray(DeterministicActorModule.forward(
        {k: jnp.asarray(vv) if not isinstance(vv, list) else vv
         for k, vv in w.items()}, jnp.asarray(test_obs)))
    mean_regret = float(np.mean(np.abs(test_obs + a)))
    assert mean_regret < 0.35, mean_regret


def test_crr_binary_mode(ray_tpu_start):
    """Binary advantage filter: weights are exact {0,1}."""
    import ray_tpu.data as rd
    from ray_tpu.rllib import CRRConfig

    rng = np.random.RandomState(1)
    n = 1024
    obs = rng.uniform(-1, 1, (n, 1)).astype(np.float32)
    act = np.clip(-obs + 0.4 * rng.randn(n, 1), -1, 1).astype(np.float32)
    rew = (-np.abs(obs + act))[:, 0].astype(np.float32)
    ds = rd.from_items(
        [{"obs": obs[i], "action": act[i], "reward": float(rew[i]),
          "next_obs": obs[(i + 1) % n], "done": 0.0}
         for i in range(n)],
        override_num_blocks=4,
    )
    cfg = CRRConfig().offline_data(ds).training(
        lr=3e-3, minibatch_size=256, gamma=0.5
    )
    cfg.weight_type = "binary"
    algo = cfg.build()
    last = algo.train()
    assert 0.0 <= last["mean_weight"] <= 1.0, last
