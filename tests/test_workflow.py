"""Durable workflow tests (ref analogue: python/ray/workflow/tests/)."""

import os

import pytest

import ray_tpu
from ray_tpu import workflow
from ray_tpu.dag import InputNode


def test_workflow_runs_dag(ray_tpu_start, tmp_path):
    @ray_tpu.remote
    def double(x):
        return x * 2

    @ray_tpu.remote
    def add(a, b):
        return a + b

    with InputNode() as inp:
        dag = add.bind(double.bind(inp), inp)
    out = workflow.run(dag, workflow_id="wf1", storage=str(tmp_path),
                       input=10)
    assert out == 30
    assert workflow.get_status("wf1", storage=str(tmp_path))["status"] == \
        "SUCCEEDED"
    assert ("wf1", "SUCCEEDED") in workflow.list_all(
        storage=str(tmp_path)
    )


def test_workflow_resume_skips_completed_steps(ray_tpu_start, tmp_path):
    """A step that failed mid-workflow re-runs on resume; completed
    upstream steps are loaded from storage, not re-executed."""
    marker = tmp_path / "executions.txt"

    @ray_tpu.remote
    def count_a():
        with open(marker, "a") as f:
            f.write("a\n")
        return 5

    @ray_tpu.remote
    def maybe_fail(x):
        if not os.path.exists(str(marker) + ".fixed"):
            raise RuntimeError("transient failure")
        return x + 1

    with InputNode() as inp:
        dag = maybe_fail.bind(count_a.bind())

    with pytest.raises(RuntimeError, match="transient failure"):
        workflow.run(dag, workflow_id="wf2", storage=str(tmp_path))
    assert workflow.get_status("wf2", storage=str(tmp_path))["status"] == \
        "FAILED"

    open(str(marker) + ".fixed", "w").close()
    out = workflow.resume("wf2", storage=str(tmp_path))
    assert out == 6
    # count_a executed exactly once across run + resume (checkpointed).
    assert open(marker).read().count("a") == 1


def test_workflow_with_actor_nodes(ray_tpu_start, tmp_path):
    """Actor-bearing DAGs run durably: actors recreate on each (re)run,
    method results checkpoint."""
    @ray_tpu.remote
    class Acc:
        def __init__(self, start):
            self.v = start

        def add(self, x):
            self.v += x
            return self.v

    with InputNode() as inp:
        acc = Acc.bind(100)
        dag = acc.add.bind(inp)
    out = workflow.run(dag, workflow_id="wfa", storage=str(tmp_path),
                       input=7)
    assert out == 107


def test_workflow_async_output_resume_all_delete(ray_tpu_start,
                                                 tmp_path):
    """run_async / get_output / resume_all / delete (ref:
    workflow/api.py run_async:174, get_output:317, resume_all:499)."""
    @ray_tpu.remote
    def double(x):
        return x * 2

    with InputNode() as inp:
        dag = double.bind(inp)

    ref = workflow.run_async(dag, workflow_id="wfa",
                             storage=str(tmp_path), input=21)
    assert ray_tpu.get(ref, timeout=60) == 42
    assert workflow.get_output("wfa", storage=str(tmp_path)) == 42

    # get_output on a non-succeeded workflow raises clearly.
    with pytest.raises(RuntimeError, match="NOT_FOUND"):
        workflow.get_output("missing", storage=str(tmp_path))

    # resume_all picks up interrupted workflows.
    marker = tmp_path / "fail_once"
    marker.write_text("x")

    @ray_tpu.remote
    def flaky(x):
        if os.path.exists(str(marker)):
            raise RuntimeError("induced")
        return x + 1

    with InputNode() as inp:
        dag2 = flaky.bind(inp)
    with pytest.raises(Exception):
        workflow.run(dag2, workflow_id="wfb", storage=str(tmp_path),
                     input=1)
    assert workflow.get_status("wfb", storage=str(tmp_path))[
        "status"] == "FAILED"
    os.remove(str(marker))
    done = dict(workflow.resume_all(storage=str(tmp_path)))
    assert done.get("wfb") == 2

    assert workflow.delete("wfa", storage=str(tmp_path))
    assert not workflow.delete("wfa", storage=str(tmp_path))
    assert workflow.get_status("wfa", storage=str(tmp_path))[
        "status"] == "NOT_FOUND"
