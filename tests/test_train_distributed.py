"""Multi-process jax.distributed rendezvous through the trainer
(VERDICT r2 ask #2b: trainer.py's ``jax.distributed.initialize`` path —
reserve_coordinator on rank 0's host + KV publication — executed for
real across two worker processes, on the CPU backend)."""

import math

import pytest

import ray_tpu
from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig


@pytest.fixture
def tpu_labeled_runtime():
    # A fake TPU resource puts workers on the use_tpu path (worker_type
    # "tpu", rendezvous enabled). JAX_PLATFORMS=cpu (conftest) keeps the
    # actual backend virtual.
    rt = ray_tpu.init(
        num_cpus=4,
        resources={"TPU": 2},
        system_config={
            "num_prestart_workers": 0,
            "heartbeat_interval_s": 0.1,
        },
    )
    yield rt
    ray_tpu.shutdown()


def test_jax_distributed_rendezvous_two_processes(tpu_labeled_runtime):
    # Defined INSIDE the test so cloudpickle ships it by value (a
    # module-level function would pickle by reference to a module the
    # worker processes cannot import).
    def distributed_loop(config):
        import jax

        from ray_tpu.train.session import get_session

        # jax.distributed.initialize already ran in the worker entry
        # (trainer.py) — the assertion below fails unless the two worker
        # processes actually rendezvoused.
        assert jax.process_count() == 2, jax.process_count()
        n_local = len(jax.local_devices())
        n_global = len(jax.devices())
        assert n_global == 2 * n_local, (n_global, n_local)

        # One real cross-process collective over the global mesh.
        import jax.numpy as jnp
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        mesh = Mesh(jax.devices(), ("dp",))
        x = jax.device_put(
            jnp.ones((n_global,), jnp.float32),
            NamedSharding(mesh, P("dp")),
        )
        total = float(jax.jit(lambda v: v.sum())(x))
        session = get_session()
        session.report({
            "total": total,
            "processes": jax.process_count(),
            "rank": session.world_rank,
        })

    trainer = JaxTrainer(
        distributed_loop,
        train_loop_config={},
        scaling_config=ScalingConfig(
            num_workers=2, use_tpu=True,
            resources_per_worker={"TPU": 1},
        ),
        run_config=RunConfig(name="rendezvous-test"),
    )
    result = trainer.fit()
    assert result.error is None, result.error
    assert result.metrics["processes"] == 2
    n = result.metrics["total"]
    assert math.isfinite(n) and n >= 2, n
