"""Thin-client mode: ray_tpu.init("rtpu://host:port") (ref analogue:
ray.init("ray://...") through util/client/ — remote driver with no local
node; object IO travels the wire)."""

import os
import subprocess
import sys
import time

import numpy as np
import pytest

import ray_tpu


@pytest.fixture
def head_cluster(tmp_path):
    env = dict(os.environ)
    env.pop("RAY_TPU_ADDRESS", None)
    log = open(tmp_path / "head.log", "wb")
    proc = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu.scripts.cli", "start", "--block",
         "--head", "--num-cpus", "2", "--port", "0"],
        stdout=log, stderr=subprocess.STDOUT, env=env,
        start_new_session=True,
    )
    address = None
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        text = (tmp_path / "head.log").read_bytes().decode(errors="ignore")
        for line in text.splitlines():
            if "head up at" in line:
                address = line.rsplit(" ", 1)[-1]
        if address:
            break
        if proc.poll() is not None:
            raise RuntimeError(f"head died:\n{text}")
        time.sleep(0.1)
    assert address, "head never published its address"
    yield address
    proc.terminate()
    try:
        proc.wait(timeout=10)
    except subprocess.TimeoutExpired:
        proc.kill()


def test_client_mode_end_to_end(head_cluster):
    rt = ray_tpu.init(address=f"rtpu://{head_cluster}")
    try:
        assert getattr(rt, "is_client", False)

        # tasks (cluster-side execution; client has no node)
        @ray_tpu.remote
        def add(a, b):
            return a + b

        assert ray_tpu.get(add.remote(2, 3), timeout=60) == 5

        # large put/get over the wire (beyond the inline threshold)
        arr = np.arange(300_000, dtype=np.int64)
        ref = ray_tpu.put(arr)
        back = ray_tpu.get(ref, timeout=60)
        assert np.array_equal(back, arr)

        # large TASK RESULT fetched over the wire
        @ray_tpu.remote
        def big():
            return np.ones(200_000, dtype=np.float64)

        out = ray_tpu.get(big.remote(), timeout=60)
        assert out.sum() == 200_000

        # actors
        @ray_tpu.remote
        class Counter:
            def __init__(self):
                self.n = 0

            def inc(self, k):
                self.n += k
                return self.n

        c = Counter.remote()
        vals = ray_tpu.get([c.inc.remote(2) for _ in range(5)], timeout=60)
        assert vals == [2, 4, 6, 8, 10]

        # chained refs as args
        assert ray_tpu.get(add.remote(ref, 1), timeout=60)[0] == 1
    finally:
        ray_tpu.shutdown()


def test_client_rejects_bad_token(head_cluster, monkeypatch):
    """Client connections honor the session-token gate."""
    # The fixture head runs without a token; simulate the inverse — a
    # client OFFERING a token connects fine (server enforces only when
    # configured), then a tokened server path is covered by test_tls's
    # infrastructure. Here: wrong-scheme address errors cleanly.
    with pytest.raises(Exception):
        ray_tpu.init(address="rtpu://127.0.0.1:1")  # nothing listening


def test_client_reconnects_after_socket_drop(head_cluster):
    """A TCP blip mid-session must not kill the thin client: the
    transport redials + re-registers and the driver resumes — including
    an idempotent request IN FLIGHT at the moment the socket dies
    (ref analogue: Ray Client reconnect, util/client/worker.py)."""
    import threading

    rt = ray_tpu.init(address=f"rtpu://{head_cluster}")
    try:
        @ray_tpu.remote
        def add(a, b):
            return a + b

        @ray_tpu.remote
        def slow():
            time.sleep(3.0)
            return "slow-done"

        assert ray_tpu.get(add.remote(1, 2), timeout=60) == 3
        ref_before = ray_tpu.put(np.arange(100_000))

        # An in-flight blocking get (idempotent get_locations/wait under
        # the hood) that must SURVIVE the drop.
        slow_ref = slow.remote()
        got = {}

        def waiter():
            got["v"] = ray_tpu.get(slow_ref, timeout=90)

        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(0.5)

        # Kill the client<->head socket underneath the runtime (network
        # blip; the head stays alive).
        raw = rt._conn._conn  # _ReconnectingConn -> Connection
        raw._sock.shutdown(__import__("socket").SHUT_RDWR)

        t.join(timeout=90)
        assert got.get("v") == "slow-done"

        # New work and pre-drop objects both resume on the new socket.
        assert ray_tpu.get(add.remote(20, 22), timeout=60) == 42
        assert ray_tpu.get(ref_before, timeout=60).sum() == \
            np.arange(100_000).sum()
    finally:
        ray_tpu.shutdown()
