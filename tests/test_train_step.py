"""Compiled train step: chunked-scan parity, sharded execution, donation,
and the HBM/fragmentation probe plumbing (ISSUE 10 tentpole)."""

import dataclasses

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from ray_tpu.models import LlamaConfig, causal_lm_loss, init_params  # noqa: E402
from ray_tpu.models.llama import scan_chunks  # noqa: E402
from ray_tpu.train.compiled_step import CompiledTrainStep  # noqa: E402


def _tiny(depth=4, **kw):
    return dataclasses.replace(LlamaConfig.tiny(), num_layers=depth, **kw)


def _loss_and_grads(cfg, params, tokens):
    return jax.jit(
        jax.value_and_grad(lambda p: causal_lm_loss(p, tokens, cfg))
    )(params)


# ------------------------------------------------------------- parity

@pytest.mark.slow
def test_scan_chunk_parity_loss_and_grads():
    """Every scan schedule (classic K=1, chunked K=2, degenerate K=L) and
    the unrolled loop compute bitwise-close loss AND grads: the chunk
    schedule is a memory layout choice, not a numerics choice."""
    base = _tiny(depth=4)
    params = init_params(base, jax.random.PRNGKey(0))
    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, 256, (2, 33))
    )
    ref_loss, ref_grads = _loss_and_grads(
        dataclasses.replace(base, scan_layers=False), params, tokens
    )
    for kw in (
        {"scan_layers": True, "scan_chunk": 0},
        {"scan_layers": True, "scan_chunk": 1},
        {"scan_layers": True, "scan_chunk": 2},
        {"scan_layers": True, "scan_chunk": 4},
        {"scan_layers": True, "scan_chunk": 2, "remat_policy": "mlp"},
        {"scan_layers": True, "scan_chunk": 2, "remat": False},
    ):
        cfg = dataclasses.replace(base, **kw)
        loss, grads = _loss_and_grads(cfg, params, tokens)
        np.testing.assert_allclose(
            float(loss), float(ref_loss), rtol=1e-6, err_msg=str(kw)
        )
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-5, atol=1e-6,
                err_msg=str(kw),
            ),
            grads, ref_grads,
        )


def test_scan_chunk_validation():
    cfg = _tiny(depth=4, scan_layers=True, scan_chunk=3)
    with pytest.raises(ValueError, match="must divide"):
        scan_chunks(cfg)
    params = init_params(_tiny(depth=4), jax.random.PRNGKey(0))
    tokens = jnp.zeros((1, 9), dtype=jnp.int32)
    with pytest.raises(ValueError, match="must divide"):
        causal_lm_loss(params, tokens, cfg)
    assert scan_chunks(_tiny(depth=6, scan_chunk=3)) == (3, 2)
    assert scan_chunks(_tiny(depth=4, scan_chunk=0)) == (1, 4)


# ------------------------------------------------- compiled step (CPU)

def test_compiled_step_smoke_and_compile_cache():
    """2-layer chunk=1 compiled step: one program, donated state, loss
    finite, no recompile on steady same-shape steps."""
    cfg = _tiny(depth=2, scan_layers=True, scan_chunk=1)
    step = CompiledTrainStep(cfg)
    params, opt_state = step.init(jax.random.PRNGKey(0))
    tokens = jnp.asarray(
        np.random.RandomState(1).randint(0, 256, (2, 17))
    )
    losses = []
    for _ in range(3):
        params, opt_state, loss = step(params, opt_state, tokens)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    # Training on one repeated batch must make progress (the optimizer
    # update really applied to the donated buffers).
    assert losses[-1] < losses[0]
    stats = step.compile_stats()
    assert stats["fn"] == "train_step"
    if stats.get("executables") is not None:
        assert stats["executables"] == 1
    assert step.num_params(params) > 0


@pytest.mark.slow
def test_compiled_step_donation_off():
    cfg = _tiny(depth=2, scan_layers=True, scan_chunk=2)
    step = CompiledTrainStep(cfg, donate=False)
    params, opt_state = step.init(jax.random.PRNGKey(0))
    tokens = jnp.zeros((1, 9), dtype=jnp.int32)
    params, opt_state, loss = step(params, opt_state, tokens)
    assert np.isfinite(float(loss))
    assert step.token_sharding() is None


@pytest.mark.slow
def test_compiled_step_chunked_matches_unrolled_training():
    """Three steps of chunked-scan training == three steps of unrolled
    training from the same init (the whole fused program is schedule-
    invariant, not just the forward)."""
    tokens = jnp.asarray(
        np.random.RandomState(2).randint(0, 256, (2, 21))
    )
    losses = {}
    for name, kw in (
        ("unrolled", {"scan_layers": False}),
        ("chunked", {"scan_layers": True, "scan_chunk": 2}),
    ):
        cfg = _tiny(depth=4, **kw)
        step = CompiledTrainStep(cfg)
        params, opt_state = step.init(jax.random.PRNGKey(3))
        out = []
        for _ in range(3):
            params, opt_state, loss = step(params, opt_state, tokens)
            out.append(float(loss))
        losses[name] = out
    np.testing.assert_allclose(
        losses["chunked"], losses["unrolled"], rtol=2e-5
    )


# ----------------------------------------------------- sharded (mesh)

@pytest.mark.slow
def test_compiled_step_sharded_matches_single_device():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    from ray_tpu.parallel import make_mesh

    cfg = _tiny(depth=4, scan_layers=True, scan_chunk=2)
    tokens = jnp.asarray(
        np.random.RandomState(4).randint(0, 256, (4, 33))
    )

    ref = CompiledTrainStep(cfg)
    p, o = ref.init(jax.random.PRNGKey(0))
    ref_losses = []
    for _ in range(2):
        p, o, loss = ref(p, o, tokens)
        ref_losses.append(float(loss))

    mesh = make_mesh(dp=2, fsdp=2, tp=2)
    step = CompiledTrainStep(cfg, mesh=mesh)
    sp, so = step.init(jax.random.PRNGKey(0))
    # The compiled init is sharding-invariant (threefry_partitionable):
    # same seed -> same model on any mesh.
    ref_embed = jax.device_get(ref.init(jax.random.PRNGKey(0))[0]["embed"])
    np.testing.assert_allclose(
        np.asarray(jax.device_get(sp["embed"])), np.asarray(ref_embed),
        rtol=1e-6,
    )
    tok = jax.device_put(tokens, step.token_sharding())
    got = []
    for _ in range(2):
        sp, so, loss = step(sp, so, tok)
        got.append(float(loss))
    np.testing.assert_allclose(got, ref_losses, rtol=1e-4)
    # Optimizer state (adam m/v) carries the SAME shardings as params —
    # the donation contract needs matching layouts on both sides.
    mu = so[0].mu
    assert (mu["layers"]["wq"].sharding
            == sp["layers"]["wq"].sharding)


# ------------------------------------------------------- HBM probe

def test_fragmentation_from_stats_preference_order():
    from ray_tpu.util.device_metrics import fragmentation_from_stats

    # peak pair preferred
    assert fragmentation_from_stats({
        "peak_bytes_in_use": 60, "peak_bytes_reserved": 100,
        "bytes_in_use": 10, "bytes_reserved": 10,
    }) == pytest.approx(0.4)
    # instantaneous pair next
    assert fragmentation_from_stats({
        "bytes_in_use": 75, "bytes_reserved": 100,
    }) == pytest.approx(0.25)
    # largest-free-block shatter estimate last
    assert fragmentation_from_stats({
        "bytes_in_use": 40, "bytes_limit": 100,
        "largest_free_block_bytes": 30,
    }) == pytest.approx(0.5)
    assert fragmentation_from_stats({}) is None


def test_hbm_snapshot_and_memory_metrics_declared():
    from ray_tpu.util import device_metrics

    snap = device_metrics.hbm_snapshot()
    assert isinstance(snap, dict)  # {} on CPU: no memory_stats
    # The fragmentation gauge is part of the declared metric surface.
    assert (device_metrics.MEMORY_FRAGMENTATION._name
            == "ray_tpu_device_memory_fragmentation_ratio")


def test_instrumented_jit_sample_memory_counts_compiles():
    from ray_tpu.util import device_metrics

    calls = {"n": 0}

    def f(x):
        calls["n"] += 1
        return x * 2

    wrapped = device_metrics.instrumented_jit(f, sample_memory=True)
    out = wrapped(jnp.asarray(3.0))
    assert float(out) == 6.0
    out = wrapped(jnp.asarray(4.0))
    assert float(out) == 8.0
    assert calls["n"] == 1  # traced once: same shape, no recompile
