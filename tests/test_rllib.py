"""RLlib tests: GAE math, PPO learning on CartPole."""

import numpy as np
import pytest

from ray_tpu.rllib.sample_batch import SampleBatch, compute_gae


def test_gae_simple():
    rewards = np.asarray([1.0, 1.0, 1.0], dtype=np.float32)
    values = np.asarray([0.0, 0.0, 0.0], dtype=np.float32)
    dones = np.asarray([False, False, True])
    out = compute_gae(rewards, values, dones, 0.0, gamma=1.0, lam=1.0)
    np.testing.assert_allclose(out["returns"], [3.0, 2.0, 1.0])


def test_gae_respects_done_boundary():
    rewards = np.asarray([1.0, 1.0], dtype=np.float32)
    values = np.asarray([0.0, 0.0], dtype=np.float32)
    dones = np.asarray([True, False])
    out = compute_gae(rewards, values, dones, 5.0, gamma=0.9, lam=1.0)
    # First transition terminal: no bootstrap across the boundary.
    np.testing.assert_allclose(out["returns"][0], 1.0)


def test_sample_batch_ops():
    b = SampleBatch({"x": np.arange(10), "y": np.arange(10) * 2})
    assert b.count == 10
    mbs = list(b.minibatches(4))
    assert len(mbs) == 2 and mbs[0].count == 4
    c = SampleBatch.concat([b, b])
    assert c.count == 20


def test_ppo_learns_cartpole(ray_tpu_start):
    pytest.importorskip("gymnasium")
    from ray_tpu.rllib import PPOConfig

    algo = (
        PPOConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=2, rollout_fragment_length=256)
        .training(lr=3e-3, train_batch_size=512, minibatch_size=128,
                  num_epochs=6)
        .debugging(seed=0)
        .build()
    )
    try:
        first = None
        best = 0.0
        for _ in range(25):
            result = algo.train()
            if first is None and result["episodes_total"] > 0:
                first = result["episode_reward_mean"]
            best = max(best, result["episode_reward_mean"])
            if best > 80:
                break
        assert first is not None
        # CartPole random play is ~20 reward; PPO should clearly improve.
        assert best > first + 30, (first, best)
        assert best > 60, (first, best)
    finally:
        algo.stop()


def test_replay_buffers_unit():
    import numpy as np

    from ray_tpu.rllib import PrioritizedReplayBuffer, ReplayBuffer
    from ray_tpu.rllib.sample_batch import SampleBatch

    buf = ReplayBuffer(capacity=100, seed=0)
    for i in range(12):
        buf.add(SampleBatch({"obs": np.full((10, 2), i, dtype=np.float32),
                             "r": np.full(10, i, dtype=np.float32)}))
    assert len(buf) == 100  # ring wrapped
    mb = buf.sample(32)
    assert mb["obs"].shape == (32, 2)

    pbuf = PrioritizedReplayBuffer(capacity=64, alpha=1.0, beta=0.4, seed=0)
    pbuf.add(SampleBatch({"r": np.arange(64, dtype=np.float32)}))
    # Give one index overwhelming priority: it should dominate samples.
    pbuf.update_priorities(np.asarray([7]), np.asarray([1e6]))
    mb = pbuf.sample(256)
    assert (mb["batch_indexes"] == 7).mean() > 0.9
    assert "weights" in mb and mb["weights"].max() <= 1.0


def test_dqn_learns_cartpole(ray_tpu_start):
    pytest.importorskip("gymnasium")
    from ray_tpu.rllib import DQNConfig

    algo = (
        DQNConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=2, rollout_fragment_length=128)
        .training(
            lr=1e-3, minibatch_size=64, buffer_size=20_000,
            num_steps_sampled_before_learning_starts=500,
            target_network_update_freq=300,
            num_updates_per_iteration=48,
            epsilon_timesteps=4_000,
            prioritized_replay=True,
        )
        .debugging(seed=0)
        .build()
    )
    try:
        first = None
        best = 0.0
        for _ in range(40):
            result = algo.train()
            if first is None and result["episodes_total"] > 0:
                first = result["episode_reward_mean"]
            best = max(best, result["episode_reward_mean"])
            if best > 80:
                break
        assert first is not None
        # Random CartPole is ~20 reward; DQN must clearly improve on it.
        assert best > 60, (first, best)
    finally:
        algo.stop()


def test_impala_learns_cartpole(ray_tpu_start):
    pytest.importorskip("gymnasium")
    from ray_tpu.rllib import IMPALAConfig

    algo = (
        IMPALAConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=2, rollout_fragment_length=256)
        .training(lr=2e-3, entropy_coeff=0.02)
        .debugging(seed=0)
        .build()
    )
    try:
        first = None
        best = 0.0
        for _ in range(120):
            result = algo.train()
            if first is None and result["episodes_total"] > 0:
                first = result["episode_reward_mean"]
            best = max(best, result["episode_reward_mean"])
            if best > 80:
                break
        assert first is not None
        # Random CartPole is ~20 reward; V-trace must clearly improve.
        assert best > 60, (first, best)
    finally:
        algo.stop()


# The toy env below lives in this (worker-unimportable) test module;
# ship it by value.
import cloudpickle as _cloudpickle
import sys as _sys

_cloudpickle.register_pickle_by_value(_sys.modules[__name__])


def _go_to_zero_env():
    """1-D continuous toy env: state x ~ U(-1,1); reward -|x + a| — the
    optimal policy is a = -x. Learnable in seconds, unlike Pendulum on a
    shared core; exercises the full SAC stack (Box space, squashed
    Gaussian, twin critics, alpha tuning). Classes live INSIDE the
    factory so cloudpickle ships them by value — the test module is not
    importable from worker processes."""
    import numpy as _np

    class _Box:
        def __init__(self, low, high, shape):
            self.low = _np.full(shape, low, dtype=_np.float32)
            self.high = _np.full(shape, high, dtype=_np.float32)
            self.shape = shape

    class GoToZero:
        def __init__(self):
            self.observation_space = _Box(-1.0, 1.0, (1,))
            self.action_space = _Box(-1.0, 1.0, (1,))
            self._rng = _np.random.RandomState(0)
            self._t = 0

        def reset(self, seed=None):
            if seed is not None:
                self._rng = _np.random.RandomState(seed)
            self._t = 0
            self._x = self._rng.uniform(-1, 1, (1,)).astype("float32")
            return self._x, {}

        def step(self, action):
            r = -float(abs(self._x[0] + float(action[0])))
            self._t += 1
            self._x = self._rng.uniform(-1, 1, (1,)).astype("float32")
            return self._x, r, False, self._t >= 50, {}

    return GoToZero()


def test_sac_learns_continuous_control(ray_tpu_start):
    """SAC on a Box action space: reward improves toward the a=-x optimum
    (ref analogue: rllib/algorithms/sac)."""
    from ray_tpu.rllib import SACConfig

    config = (
        SACConfig()
        .environment(_go_to_zero_env)
        .env_runners(num_env_runners=2, rollout_fragment_length=100)
        .training(lr=3e-3, minibatch_size=128,
                  num_updates_per_iteration=40,
                  num_steps_sampled_before_learning_starts=200)
    )
    algo = config.build()
    try:
        first = algo.train()
        last = {}
        for _ in range(12):
            last = algo.train()
        assert last["num_learner_updates"] > 0
        assert np.isfinite(last["loss"]) and last["alpha"] > 0
        # Random policy averages about -0.66/step (-33/episode); the
        # optimum is 0. Require clear movement toward it.
        assert last["episode_reward_mean"] > \
            first["episode_reward_mean"] + 5, (first, last)
        assert last["episode_reward_mean"] > -25, last
    finally:
        algo.stop()
