"""RLlib tests: GAE math, PPO learning on CartPole."""

import numpy as np
import pytest

from ray_tpu.rllib.sample_batch import SampleBatch, compute_gae


def test_gae_simple():
    rewards = np.asarray([1.0, 1.0, 1.0], dtype=np.float32)
    values = np.asarray([0.0, 0.0, 0.0], dtype=np.float32)
    dones = np.asarray([False, False, True])
    out = compute_gae(rewards, values, dones, 0.0, gamma=1.0, lam=1.0)
    np.testing.assert_allclose(out["returns"], [3.0, 2.0, 1.0])


def test_gae_respects_done_boundary():
    rewards = np.asarray([1.0, 1.0], dtype=np.float32)
    values = np.asarray([0.0, 0.0], dtype=np.float32)
    dones = np.asarray([True, False])
    out = compute_gae(rewards, values, dones, 5.0, gamma=0.9, lam=1.0)
    # First transition terminal: no bootstrap across the boundary.
    np.testing.assert_allclose(out["returns"][0], 1.0)


def test_sample_batch_ops():
    b = SampleBatch({"x": np.arange(10), "y": np.arange(10) * 2})
    assert b.count == 10
    mbs = list(b.minibatches(4))
    assert len(mbs) == 2 and mbs[0].count == 4
    c = SampleBatch.concat([b, b])
    assert c.count == 20


def test_ppo_learns_cartpole(ray_tpu_start):
    pytest.importorskip("gymnasium")
    from ray_tpu.rllib import PPOConfig

    algo = (
        PPOConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=2, rollout_fragment_length=256)
        .training(lr=3e-3, train_batch_size=512, minibatch_size=128,
                  num_epochs=6)
        .debugging(seed=0)
        .build()
    )
    try:
        first = None
        best = 0.0
        for _ in range(25):
            result = algo.train()
            if first is None and result["episodes_total"] > 0:
                first = result["episode_reward_mean"]
            best = max(best, result["episode_reward_mean"])
            if best > 80:
                break
        assert first is not None
        # CartPole random play is ~20 reward; PPO should clearly improve.
        assert best > first + 30, (first, best)
        assert best > 60, (first, best)
    finally:
        algo.stop()
