"""RLlib tests: GAE math, PPO learning on CartPole."""

import numpy as np
import pytest

from ray_tpu.rllib.sample_batch import SampleBatch, compute_gae


def test_gae_simple():
    rewards = np.asarray([1.0, 1.0, 1.0], dtype=np.float32)
    values = np.asarray([0.0, 0.0, 0.0], dtype=np.float32)
    dones = np.asarray([False, False, True])
    out = compute_gae(rewards, values, dones, 0.0, gamma=1.0, lam=1.0)
    np.testing.assert_allclose(out["returns"], [3.0, 2.0, 1.0])


def test_gae_respects_done_boundary():
    rewards = np.asarray([1.0, 1.0], dtype=np.float32)
    values = np.asarray([0.0, 0.0], dtype=np.float32)
    dones = np.asarray([True, False])
    out = compute_gae(rewards, values, dones, 5.0, gamma=0.9, lam=1.0)
    # First transition terminal: no bootstrap across the boundary.
    np.testing.assert_allclose(out["returns"][0], 1.0)


def test_sample_batch_ops():
    b = SampleBatch({"x": np.arange(10), "y": np.arange(10) * 2})
    assert b.count == 10
    mbs = list(b.minibatches(4))
    assert len(mbs) == 2 and mbs[0].count == 4
    c = SampleBatch.concat([b, b])
    assert c.count == 20


def test_ppo_learns_cartpole(ray_tpu_start):
    pytest.importorskip("gymnasium")
    from ray_tpu.rllib import PPOConfig

    algo = (
        PPOConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=2, rollout_fragment_length=256)
        .training(lr=3e-3, train_batch_size=512, minibatch_size=128,
                  num_epochs=6)
        .debugging(seed=0)
        .build()
    )
    try:
        first = None
        best = 0.0
        for _ in range(25):
            result = algo.train()
            if first is None and result["episodes_total"] > 0:
                first = result["episode_reward_mean"]
            best = max(best, result["episode_reward_mean"])
            if best > 80:
                break
        assert first is not None
        # CartPole random play is ~20 reward; PPO should clearly improve.
        assert best > first + 30, (first, best)
        assert best > 60, (first, best)
    finally:
        algo.stop()


def test_replay_buffers_unit():
    import numpy as np

    from ray_tpu.rllib import PrioritizedReplayBuffer, ReplayBuffer
    from ray_tpu.rllib.sample_batch import SampleBatch

    buf = ReplayBuffer(capacity=100, seed=0)
    for i in range(12):
        buf.add(SampleBatch({"obs": np.full((10, 2), i, dtype=np.float32),
                             "r": np.full(10, i, dtype=np.float32)}))
    assert len(buf) == 100  # ring wrapped
    mb = buf.sample(32)
    assert mb["obs"].shape == (32, 2)

    pbuf = PrioritizedReplayBuffer(capacity=64, alpha=1.0, beta=0.4, seed=0)
    pbuf.add(SampleBatch({"r": np.arange(64, dtype=np.float32)}))
    # Give one index overwhelming priority: it should dominate samples.
    pbuf.update_priorities(np.asarray([7]), np.asarray([1e6]))
    mb = pbuf.sample(256)
    assert (mb["batch_indexes"] == 7).mean() > 0.9
    assert "weights" in mb and mb["weights"].max() <= 1.0


@pytest.mark.slow
def test_dqn_learns_cartpole(ray_tpu_start):
    pytest.importorskip("gymnasium")
    from ray_tpu.rllib import DQNConfig

    algo = (
        DQNConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=2, rollout_fragment_length=128)
        .training(
            lr=1e-3, minibatch_size=64, buffer_size=20_000,
            num_steps_sampled_before_learning_starts=500,
            target_network_update_freq=300,
            num_updates_per_iteration=48,
            epsilon_timesteps=4_000,
            prioritized_replay=True,
        )
        .debugging(seed=0)
        .build()
    )
    try:
        first = None
        best = 0.0
        for _ in range(40):
            result = algo.train()
            if first is None and result["episodes_total"] > 0:
                first = result["episode_reward_mean"]
            best = max(best, result["episode_reward_mean"])
            if best > 80:
                break
        assert first is not None
        # Random CartPole is ~20 reward; DQN must clearly improve on it.
        assert best > 60, (first, best)
    finally:
        algo.stop()


@pytest.mark.slow
def test_impala_learns_cartpole(ray_tpu_start):
    pytest.importorskip("gymnasium")
    from ray_tpu.rllib import IMPALAConfig

    algo = (
        IMPALAConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=2, rollout_fragment_length=256)
        .training(lr=2e-3, entropy_coeff=0.02)
        .debugging(seed=0)
        .build()
    )
    try:
        first = None
        best = 0.0
        for _ in range(120):
            result = algo.train()
            if first is None and result["episodes_total"] > 0:
                first = result["episode_reward_mean"]
            best = max(best, result["episode_reward_mean"])
            if best > 80:
                break
        assert first is not None
        # Random CartPole is ~20 reward; V-trace must clearly improve.
        assert best > 60, (first, best)
    finally:
        algo.stop()


# The toy env below lives in this (worker-unimportable) test module;
# ship it by value.
import cloudpickle as _cloudpickle
import sys as _sys

_cloudpickle.register_pickle_by_value(_sys.modules[__name__])


def _go_to_zero_env():
    """1-D continuous toy env: state x ~ U(-1,1); reward -|x + a| — the
    optimal policy is a = -x. Learnable in seconds, unlike Pendulum on a
    shared core; exercises the full SAC stack (Box space, squashed
    Gaussian, twin critics, alpha tuning). Classes live INSIDE the
    factory so cloudpickle ships them by value — the test module is not
    importable from worker processes."""
    import numpy as _np

    class _Box:
        def __init__(self, low, high, shape):
            self.low = _np.full(shape, low, dtype=_np.float32)
            self.high = _np.full(shape, high, dtype=_np.float32)
            self.shape = shape

    class GoToZero:
        def __init__(self):
            self.observation_space = _Box(-1.0, 1.0, (1,))
            self.action_space = _Box(-1.0, 1.0, (1,))
            self._rng = _np.random.RandomState(0)
            self._t = 0

        def reset(self, seed=None):
            if seed is not None:
                self._rng = _np.random.RandomState(seed)
            self._t = 0
            self._x = self._rng.uniform(-1, 1, (1,)).astype("float32")
            return self._x, {}

        def step(self, action):
            r = -float(abs(self._x[0] + float(action[0])))
            self._t += 1
            self._x = self._rng.uniform(-1, 1, (1,)).astype("float32")
            return self._x, r, False, self._t >= 50, {}

    return GoToZero()


@pytest.mark.slow
def test_sac_learns_continuous_control(ray_tpu_start):
    """SAC on a Box action space: reward improves toward the a=-x optimum
    (ref analogue: rllib/algorithms/sac)."""
    from ray_tpu.rllib import SACConfig

    config = (
        SACConfig()
        .environment(_go_to_zero_env)
        .env_runners(num_env_runners=2, rollout_fragment_length=100)
        .training(lr=3e-3, minibatch_size=128,
                  num_updates_per_iteration=40,
                  num_steps_sampled_before_learning_starts=200)
    )
    algo = config.build()
    try:
        first = algo.train()
        last = {}
        for _ in range(12):
            last = algo.train()
        assert last["num_learner_updates"] > 0
        assert np.isfinite(last["loss"]) and last["alpha"] > 0
        # Random policy averages about -0.66/step (-33/episode); the
        # optimum is 0. Require clear movement toward it.
        assert last["episode_reward_mean"] > \
            first["episode_reward_mean"] + 5, (first, last)
        assert last["episode_reward_mean"] > -25, last
    finally:
        algo.stop()


@pytest.mark.slow
def test_bc_offline_discrete(ray_tpu_start):
    """Offline behavior cloning from a ray_tpu.data Dataset: the cloned
    policy reproduces a deterministic expert (ref: rllib/algorithms/bc
    over the offline data stack)."""
    import ray_tpu.data as rd
    from ray_tpu.rllib import BCConfig

    rng = np.random.RandomState(0)
    obs = rng.randn(1024, 4).astype("float32")
    expert_actions = (obs[:, 0] + obs[:, 1] > 0).astype("int64")
    ds = rd.from_items(
        [{"obs": obs[i], "action": int(expert_actions[i])}
         for i in range(len(obs))],
        override_num_blocks=4,
    )
    config = BCConfig().offline_data(ds).training(
        lr=5e-3, minibatch_size=256
    )
    config.num_actions = 2
    bc = config.build()
    last = {}
    for _ in range(25):
        last = bc.train()
    assert last["num_rows_trained"] == 1024
    assert last["loss"] < 0.3, last

    policy = bc.get_policy()
    test_obs = rng.randn(256, 4).astype("float32")
    want = (test_obs[:, 0] + test_obs[:, 1] > 0).astype("int64")
    logits, _ = policy.logits_and_value(test_obs)
    got = logits.argmax(axis=1)
    assert (got == want).mean() > 0.9, (got[:10], want[:10])


@pytest.mark.slow
def test_bc_offline_continuous(ray_tpu_start):
    """Continuous BC: squashed-mean regression toward a = -obs."""
    import ray_tpu.data as rd
    from ray_tpu.rllib import BCConfig

    rng = np.random.RandomState(1)
    obs = rng.uniform(-0.8, 0.8, size=(512, 1)).astype("float32")
    ds = rd.from_items(
        [{"obs": obs[i], "action": (-obs[i]).astype("float32")}
         for i in range(len(obs))],
        override_num_blocks=2,
    )
    config = BCConfig().offline_data(ds).training(
        lr=5e-3, minibatch_size=128
    )
    config.action_space = "continuous"
    bc = config.build()
    for _ in range(40):
        last = bc.train()
    assert last["loss"] < 0.02, last


def _two_team_env():
    """Two-agent cooperative toy: each agent sees [signal] and must pick
    action == sign(signal) to score; reward shared. By-value classes
    (worker-unimportable test module)."""
    import numpy as _np

    class TwoTeam:
        def __init__(self):
            self._rng = _np.random.RandomState(0)
            self._t = 0

        def _obs(self):
            self._sig = self._rng.choice([-1.0, 1.0], size=2)
            return {f"agent_{i}": _np.asarray([self._sig[i]], "float32")
                    for i in range(2)}

        def reset(self, seed=None):
            if seed is not None:
                self._rng = _np.random.RandomState(seed)
            self._t = 0
            return self._obs(), {}

        def step(self, actions):
            rew = {}
            for i in range(2):
                want = 1 if self._sig[i] > 0 else 0
                rew[f"agent_{i}"] = 1.0 if actions[f"agent_{i}"] == want \
                    else -1.0
            self._t += 1
            done = self._t >= 25
            obs = self._obs()
            return (obs, rew,
                    {"__all__": done}, {"__all__": False}, {})

    return TwoTeam()


@pytest.mark.slow
def test_multi_agent_ppo_shared_policy(ray_tpu_start):
    """Multi-agent PPO with a shared policy learns the signal-matching
    task (ref: MultiAgentEnv + policy_mapping_fn)."""
    from ray_tpu.rllib import MultiAgentPPOConfig

    config = (
        MultiAgentPPOConfig()
        .environment(_two_team_env)
        .env_runners(num_env_runners=2, rollout_fragment_length=100)
        .training(lr=5e-3, minibatch_size=64, num_epochs=4)
        .multi_agent(
            policies={"shared": {"obs_dim": 1, "num_actions": 2}},
            policy_mapping_fn=lambda aid: "shared",
        )
    )
    algo = config.build()
    try:
        last = {}
        for _ in range(12):
            last = algo.train()
        # Random play averages 0/step; the optimum is +1/step per agent
        # (50/episode for the pair over 25 steps).
        assert last["episode_reward_mean"] > 25, last
        assert "shared/loss" in last
        assert set(algo.get_weights()) == {"shared"}
    finally:
        algo.stop()


@pytest.mark.slow
def test_multi_agent_independent_policies(ray_tpu_start):
    """Distinct policy ids train independent weights."""
    from ray_tpu.rllib import MultiAgentPPOConfig

    config = (
        MultiAgentPPOConfig()
        .environment(_two_team_env)
        .env_runners(num_env_runners=1, rollout_fragment_length=50)
        .training(lr=5e-3, minibatch_size=32, num_epochs=2)
        .multi_agent(
            policies={"p0": {"obs_dim": 1, "num_actions": 2},
                      "p1": {"obs_dim": 1, "num_actions": 2}},
            policy_mapping_fn=lambda aid: "p" + aid[-1],
        )
    )
    algo = config.build()
    try:
        out = algo.train()
        assert "p0/loss" in out and "p1/loss" in out
        w = algo.get_weights()
        assert set(w) == {"p0", "p1"}
    finally:
        algo.stop()


@pytest.mark.slow
def test_appo_async_learns_cartpole(ray_tpu_start):
    """APPO: asynchronous sampling (runners never barrier) + IS-clipped
    PPO loss on the shared Learner layer; reward improves (ref:
    rllib/algorithms/appo)."""
    pytest.importorskip("gymnasium")
    from ray_tpu.rllib import APPOConfig

    algo = (
        APPOConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=2, rollout_fragment_length=200)
        .training(lr=1e-3, batches_per_iteration=6,
                  broadcast_interval=2)
        .debugging(seed=0)
        .build()
    )
    try:
        first = algo.train()
        last = {}
        for _ in range(14):
            last = algo.train()
        assert last["num_learner_updates"] > first["num_learner_updates"]
        assert np.isfinite(last["total_loss"])
        assert last["mean_is_ratio"] > 0
        assert last["episode_reward_mean"] > max(
            40.0, first["episode_reward_mean"] + 15
        ), (first["episode_reward_mean"], last["episode_reward_mean"])
    finally:
        algo.stop()


@pytest.mark.slow
def test_appo_remote_learner_group(ray_tpu_start):
    """LearnerGroup remote mode: the learner lives in its own actor
    (the learner/actor split), and training still advances."""
    pytest.importorskip("gymnasium")
    from ray_tpu.rllib import APPOConfig

    algo = (
        APPOConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=1, rollout_fragment_length=100)
        .training(batches_per_iteration=3, remote_learner=True)
        .build()
    )
    try:
        out = algo.train()
        assert out["num_learner_updates"] >= 3
        w = algo.get_weights()
        assert "pi" in w and "trunk" in w
    finally:
        algo.stop()


@pytest.mark.slow
def test_td3_learns_continuous_control(ray_tpu_start):
    """TD3 on a Box action space: twin critics + delayed deterministic
    actor move reward toward the a=-x optimum (ref:
    rllib/algorithms/td3)."""
    from ray_tpu.rllib import TD3Config

    config = (
        TD3Config()
        .environment(_go_to_zero_env)
        .env_runners(num_env_runners=2, rollout_fragment_length=100)
        .training(lr=3e-3, minibatch_size=128,
                  num_updates_per_iteration=60,
                  num_steps_sampled_before_learning_starts=200,
                  exploration_noise=0.2)
    )
    algo = config.build()
    try:
        first = algo.train()
        last = {}
        for _ in range(15):
            last = algo.train()
        assert last["num_learner_updates"] > 0
        assert np.isfinite(last["critic_loss"])
        assert "actor_loss" in last
        # Convergence measured on this env: -18 -> ~-8 over 16 iters
        # (episode_reward_mean is a running average and lags).
        assert last["episode_reward_mean"] > \
            first["episode_reward_mean"] + 4, (first, last)
        assert last["episode_reward_mean"] > -12, last
    finally:
        algo.stop()


def test_learner_layer_unit():
    """The shared Learner: polyak targets move toward params, grad
    steps reduce a quadratic loss, weights round-trip."""
    import jax.numpy as jnp

    from ray_tpu.rllib.core import Learner

    class Quad(Learner):
        def compute_loss(self, params, target, batch):
            w = params["w"][0][0]
            loss = ((w - batch["target_w"]) ** 2).sum()
            return loss, {"dist": loss}

    w0 = np.ones((2, 2), dtype=np.float32)
    lrn = Quad({"w": [(w0, np.zeros(2, np.float32))]},
               lr=0.1, target_keys=("w",), tau=0.5)
    tgt = {"target_w": np.full((2, 2), 3.0, np.float32)}
    first = lrn.update(tgt)
    for _ in range(50):
        last = lrn.update(tgt)
    assert last["dist"] < first["dist"] * 0.01
    got = lrn.get_weights()["w"][0][0]
    np.testing.assert_allclose(got, 3.0, atol=0.2)
    # target tracked params through polyak updates
    tw = np.asarray(lrn._target["w"][0][0])
    np.testing.assert_allclose(tw, got, atol=0.3)
    # round-trip
    lrn.set_weights(lrn.get_weights())
    assert lrn.update(tgt)["dist"] <= last["dist"] * 1.5


@pytest.mark.slow
def test_cql_offline_continuous(ray_tpu_start):
    """CQL trains offline from a transitions Dataset: TD loss falls, the
    conservative penalty is active, and the learned deterministic actor
    beats the behavior policy's value on the a=-x task (ref:
    rllib/algorithms/cql)."""
    import ray_tpu.data as rd
    from ray_tpu.rllib import CQLConfig

    rng = np.random.RandomState(0)
    n = 4000
    obs = rng.uniform(-1, 1, (n, 1)).astype(np.float32)
    # Behavior policy: noisy version of the optimal a = -x.
    act = np.clip(-obs + 0.3 * rng.randn(n, 1), -1, 1).astype(np.float32)
    rew = (-np.abs(obs + act))[:, 0].astype(np.float32)
    next_obs = rng.uniform(-1, 1, (n, 1)).astype(np.float32)
    done = np.zeros(n, np.float32)
    ds = rd.from_items(
        [{"obs": obs[i], "action": act[i], "reward": float(rew[i]),
          "next_obs": next_obs[i], "done": float(done[i])}
         for i in range(n)],
        override_num_blocks=8,
    )
    algo = (
        CQLConfig()
        .offline_data(ds)
        .training(lr=3e-3, minibatch_size=256, gamma=0.5,
                  cql_alpha=0.5)
        .build()
    )
    first = algo.train()
    last = {}
    for _ in range(6):
        last = algo.train()
    assert last["num_learner_updates"] > 0
    assert np.isfinite(last["td_loss"]) and np.isfinite(
        last["cql_penalty"]
    )
    assert last["td_loss"] < first["td_loss"], (first, last)

    # The distilled actor should act close to a=-x on held-out states.
    from ray_tpu.rllib.core import DeterministicActorModule
    import jax.numpy as jnp

    w = algo.get_weights()
    test_obs = np.linspace(-0.9, 0.9, 21, dtype=np.float32)[:, None]
    a = np.asarray(DeterministicActorModule.forward(
        {k: jnp.asarray(vv) if not isinstance(vv, list) else vv
         for k, vv in w.items()}, jnp.asarray(test_obs)))
    mean_regret = float(np.mean(np.abs(test_obs + a)))
    assert mean_regret < 0.35, mean_regret


def test_twin_critic_learner_roundtrip():
    """TwinCriticLearner (shared by TD3/CQL): set_weights(get_weights())
    must NOT drop the critics, and get_state snapshots the full tree."""
    from ray_tpu.rllib.core import (
        DeterministicActorModule,
        TwinCriticLearner,
    )

    class L(TwinCriticLearner):
        def compute_loss(self, params, target, batch):
            import jax.numpy as jnp

            from ray_tpu.rllib.core import QModule

            q = QModule.forward(params["q1"], batch["obs"],
                                batch["act"])
            return (q ** 2).mean(), {"q": q.mean()}

    lrn = L(DeterministicActorModule(3, 2, 16, 0).init_params(),
            obs_dim=3, act_dim=2, hidden=16, lr=1e-3, tau=0.1, seed=0)
    batch = {"obs": np.zeros((4, 3), np.float32),
             "act": np.zeros((4, 2), np.float32)}
    lrn.update(batch)
    w = lrn.get_weights()           # actor-only view for rollouts
    assert "mu" in w and "q1" not in w
    lrn.set_weights(w)              # must merge, not replace
    lrn.update(batch)               # would KeyError if critics dropped
    lrn.actor_update(batch)
    st = lrn.get_state()
    assert set(st["params"]) == {"actor", "q1", "q2"}
    assert set(st["target"]) == {"actor", "q1", "q2"}
