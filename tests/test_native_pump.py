"""Native frame pump (src/pump/ + core/frame_pump.py): codec parity,
seq dispatch, framing, end-to-end direct-plane engagement, forced
pure-Python fallback, and chaos/exactly-once with the pump engaged.

The codec fuzz holds the C encoders and the pure-Python mirror
byte-identical in BOTH directions — the wire layout is the contract that
lets a native caller talk to a mirror-decoding peer (and the sniffing in
protocol.loads_msg depends on both producing the same dict shapes)."""

import os
import random
import socket
import subprocess
import sys
import time

import pytest

import ray_tpu
from ray_tpu.core import frame_pump
from ray_tpu.core.ids import ObjectID, TaskID
from ray_tpu.core.object_store import InlineLocation
from ray_tpu.core.protocol import Connection, ConnectionClosed
from ray_tpu.core.task_spec import RefArg, ValueArg

needs_native = pytest.mark.skipif(
    not frame_pump.available(), reason="native pump extension unavailable"
)


def _rand_call(rng):
    tmpl = rng.randrange(1, 1 << 16)
    tid = rng.randbytes(16)
    seq = rng.randrange(1, 1 << 48)
    deadline = rng.choice([0.0, rng.random() * 1e9])
    args = []
    for _ in range(rng.randrange(0, 4)):
        if rng.random() < 0.5:
            args.append(RefArg(ObjectID(rng.randbytes(20))))
        else:
            args.append(ValueArg(rng.randbytes(rng.randrange(0, 200))))
    kwargs = {}
    for i in range(rng.randrange(0, 3)):
        k = f"k{i}_{rng.randrange(100)}"
        kwargs[k] = (RefArg(ObjectID(rng.randbytes(20)))
                     if rng.random() < 0.5
                     else ValueArg(rng.randbytes(rng.randrange(0, 50))))
    nested = tuple(
        ObjectID(rng.randbytes(20)) for _ in range(rng.randrange(0, 3))
    )
    # Codec v2: call frames may carry (trace_id, span_id); a parentless
    # root stamps an empty span id, so fuzz that shape too.
    trace = rng.choice([
        None,
        (rng.randbytes(16).hex(), rng.randbytes(8).hex()),
        (rng.randbytes(16).hex(), ""),
    ])
    return tmpl, tid, seq, deadline, args, kwargs, nested, trace


def _rand_done(rng):
    results = [
        (ObjectID(rng.randbytes(20)),
         InlineLocation(rng.randbytes(rng.randrange(0, 300))))
        for _ in range(rng.randrange(0, 4))
    ]
    return {
        "type": "task_done",
        "task_id": TaskID(rng.randbytes(16)),
        "results": results,
        "failed": False,
        "duration_s": rng.random(),
    }


@needs_native
def test_codec_parity_fuzz():
    """Random call/done/fence frames: native and Python encoders emit
    byte-identical frames, and each decoder reads the other's output."""
    mod = frame_pump._module()
    rng = random.Random(0xC0DEC)
    for _ in range(300):
        (tmpl, tid, seq, deadline, args, kwargs, nested,
         trace) = _rand_call(rng)
        nat = mod.encode_call(tmpl, tid, seq, deadline, args, kwargs,
                              nested, trace)
        pyb = frame_pump.py_encode_call(tmpl, tid, seq, deadline, args,
                                        kwargs, nested, trace)
        assert nat == pyb
        d_nat = mod.decode(pyb)
        d_py = frame_pump.py_decode(nat)
        assert d_nat == d_py
        assert d_nat["t"] == tmpl and d_nat["i"] == tid and d_nat["q"] == seq
        if deadline:
            assert d_nat["d"] == deadline
        if args or kwargs:
            got_args, got_kwargs = d_nat["a"]
            assert got_args == args and got_kwargs == kwargs
        if nested:
            assert d_nat["n"] == nested
        if trace is None:
            assert "tc" not in d_nat
        else:
            assert d_nat["tc"] == trace

        done = _rand_done(rng)
        nat = mod.encode_done(done)
        pyb = frame_pump.py_encode_done(done)
        assert nat == pyb and nat is not None
        assert mod.decode(pyb) == frame_pump.py_decode(nat)
        assert mod.decode(nat)["task_id"] == done["task_id"]

        batch = [_rand_done(rng) for _ in range(rng.randrange(1, 5))]
        nat = mod.encode_done_batch(batch)
        pyb = frame_pump.py_encode_done_batch(batch)
        assert nat == pyb
        decoded = mod.decode(pyb)
        assert decoded["type"] == "task_done_batch"
        assert len(decoded["items"]) == len(batch)

        mid = rng.randrange(1, 1 << 32)
        assert mod.encode_fence(mid) == frame_pump.py_encode_fence(mid)
        assert (mod.encode_fence_ack(mid)
                == frame_pump.py_encode_fence_ack(mid))
        assert mod.decode(frame_pump.py_encode_fence(mid)) == {
            "type": "fence", "msg_id": mid}


@needs_native
def test_codec_unsupported_shapes_fall_back():
    """Shapes outside the hot dialect return None from BOTH encoders
    (the caller then rides pickle for that frame), and malformed frames
    raise instead of decoding garbage."""
    mod = frame_pump._module()
    tid = b"T" * 16
    done = _rand_done(random.Random(1))
    for bad in (
        {**done, "failed": True},
        {**done, "nested": [(ObjectID(b"O" * 20), [])]},
        {**done, "error_type": "ValueError"},
        {**done, "results": [(ObjectID(b"O" * 20), object())]},
    ):
        assert mod.encode_done(bad) is None
        assert frame_pump.py_encode_done(bad) is None
        assert mod.encode_done_batch([done, bad]) is None
        assert frame_pump.py_encode_done_batch([done, bad]) is None
    # Unsupported arg kind: both sides refuse.
    assert mod.encode_call(1, tid, 1, 0.0, [object()], {}, ()) is None
    assert frame_pump.py_encode_call(1, tid, 1, 0.0, [object()], {},
                                     ()) is None
    # Truncated frames raise in both decoders.
    frame = mod.encode_call(1, tid, 7, 0.0, None, None, None)
    for cut in (frame[:1], frame[:5], frame[:-3], b"\xa7\x7f"):
        with pytest.raises(ValueError):
            mod.decode(cut)
        with pytest.raises(ValueError):
            frame_pump.py_decode(cut)


@needs_native
def test_seq_queue_native_matches_python():
    """Random permutations + duplicate replays: the extension queue and
    PySeqQueue admit identical runnable sequences with identical parking
    and duplicate-drop behavior."""
    mod = frame_pump._module()
    rng = random.Random(7)
    for _ in range(20):
        nat, py = mod.seq_queue(), frame_pump.PySeqQueue()
        seqs = list(range(1, 65))
        rng.shuffle(seqs)
        # Sprinkle duplicate deliveries (failover replays).
        deliveries = seqs + [rng.choice(seqs) for _ in range(10)]
        out_nat, out_py = [], []
        for s in deliveries:
            out_nat.extend(nat.push(s, s))
            out_py.extend(py.push(s, s))
            assert nat.parked == py.parked
            assert nat.expected == py.expected
        assert out_nat == out_py == list(range(1, 65))


def _done_for(tid: bytes, rng):
    results = [
        (ObjectID(rng.randbytes(20)),
         InlineLocation(rng.randbytes(rng.randrange(0, 100))))
        for _ in range(rng.randrange(0, 3))
    ]
    return {
        "type": "task_done",
        "task_id": TaskID(tid),
        "results": results,
        "failed": False,
        "duration_s": rng.random(),
    }


@needs_native
def test_pending_table_native_matches_python_fuzz():
    """Random interleavings of submit / complete (direct pop AND
    DONE/DONE_BATCH frame application) / duplicate completion /
    backpressure probe / death-drain: the extension table and
    PyPendingTable stay observationally identical — sizes, pop results,
    wait outcomes, and seq-ordered drain snapshots all match."""
    mod = frame_pump._module()
    rng = random.Random(0xF00D)
    for _round in range(12):
        nat, py = mod.pending_table(), frame_pump.PyPendingTable()
        live = []
        seq = 0
        for _op in range(400):
            r = rng.random()
            if r < 0.45 or not live:
                seq += 1
                tid = rng.randbytes(16)
                live.append(tid)
                assert nat.add(tid, seq) == py.add(tid, seq)
            elif r < 0.70:
                tid = live.pop(rng.randrange(len(live)))
                if rng.random() < 0.5:
                    assert nat.pop(tid) == py.pop(tid)
                else:
                    done = _done_for(tid, rng)
                    payload = (mod.encode_done(done)
                               if rng.random() < 0.5
                               else mod.encode_done_batch([done]))
                    # Byte-identical payloads both directions feed the
                    # same native application path.
                    assert payload == (
                        frame_pump.py_encode_done(done)
                        if payload[1] == frame_pump.F_DONE
                        else frame_pump.py_encode_done_batch([done]))
                    assert nat.apply_done(payload) == py.apply_done(payload)
            elif r < 0.80:
                # Unknown/duplicate completion: a miss on both sides.
                tid = rng.randbytes(16)
                assert nat.pop(tid) is None and py.pop(tid) is None
            elif r < 0.92:
                assert (nat.wait_below(1 << 30, 0.0)
                        == py.wait_below(1 << 30, 0.0)
                        == len(live))
                assert len(nat) == len(py) == len(live)
            else:
                # Injected channel death: drain snapshots must be
                # byte-identical AND in seq order on both sides.
                assert nat.drain() == py.drain()
                live.clear()
        assert nat.drain() == py.drain()
        assert len(nat) == len(py) == 0
        ns, ps = nat.stats(), py.stats()
        assert set(ns) == set(ps) == {"adds", "pops", "applies",
                                      "wakeups", "misses"}
        assert ns["adds"] == ps["adds"] and ns["misses"] == ps["misses"]


@needs_native
def test_pending_table_backpressure_cap():
    """wait_below parks (GIL released) until a completion pops the
    table below the cap — and fail() releases a parked submitter
    immediately, the injected-channel-death contract."""
    import threading

    mod = frame_pump._module()
    for table in (mod.pending_table(), frame_pump.PyPendingTable()):
        for i in range(8):
            table.add(b"%016d" % i, i + 1)
        t0 = time.perf_counter()
        assert table.wait_below(8, 0.05) == 8  # times out at the cap
        assert time.perf_counter() - t0 >= 0.04

        released = threading.Event()

        def parked():
            while table.size() >= 8 and not table.failed:
                table.wait_below(8, 5.0)
            released.set()

        t = threading.Thread(target=parked, daemon=True)
        t.start()
        time.sleep(0.05)
        assert not released.is_set()
        table.pop(b"%016d" % 0)  # completion signals the condvar
        assert released.wait(2.0), "pop did not wake the capped submitter"
        # Refill to the cap, then kill the channel: fail() must release.
        table.add(b"%016d" % 99, 100)
        released.clear()
        t = threading.Thread(target=parked, daemon=True)
        t.start()
        time.sleep(0.05)
        table.fail()
        assert released.wait(2.0), "fail() did not wake the submitter"


@needs_native
def test_waiter_table_native_matches_python():
    """Random put/get/pop/mark_resolved with a small cap: the native
    WaiterTable and PyWaiterTable agree on membership, identity of the
    returned entries, and the resolved-FIFO eviction discipline."""
    mod = frame_pump._module()
    rng = random.Random(0xBEEF)
    nat, py = mod.waiter_table(16), frame_pump.PyWaiterTable(16)
    keys = [rng.randbytes(20) for _ in range(200)]
    entries = {k: object() for k in keys}
    inserted = []
    for k in keys:
        r = rng.random()
        if r < 0.6:
            nat.put(k, entries[k])
            py.put(k, entries[k])
            inserted.append(k)
            if rng.random() < 0.7:
                nat.mark_resolved(k)
                py.mark_resolved(k)
        elif inserted:
            probe = rng.choice(inserted)
            if r < 0.8:
                assert nat.get(probe) is py.get(probe)
            else:
                assert nat.pop(probe) is py.pop(probe)
        assert len(nat) == len(py)
    for k in keys:
        assert nat.get(k) is py.get(k)


@needs_native
def test_recv_burst_applies_and_splits(ray_tpu_start=None):
    """recv_burst: one call drains an arrived-together burst, applies
    native completions to the pending table off-GIL, and hands back
    non-done payloads raw (pickle frames, fences) for Python dispatch."""
    from ray_tpu.core.protocol import dumps_msg

    mod = frame_pump._module()
    a, b = socket.socketpair()
    try:
        ca, cb = mod.chan(a.fileno()), mod.chan(b.fileno())
        rng = random.Random(3)
        table = mod.pending_table()
        d1, d2 = _done_for(b"A" * 16, rng), _done_for(b"B" * 16, rng)
        table.add(b"A" * 16, 1)
        table.add(b"B" * 16, 2)
        table.add(b"C" * 16, 3)
        ca.send_many([
            mod.encode_done(d1),
            dumps_msg({"type": "fence_ack", "msg_id": 5}),
            mod.encode_done_batch([d2]),
            mod.encode_fence(9),
        ])
        dones, others = cb.recv_burst(table)
        assert [d["task_id"] for d in dones] == [d1["task_id"],
                                                 d2["task_id"]]
        assert len(others) == 2
        assert table.size() == 1 and table.pop(b"C" * 16) == 3
        assert table.stats()["applies"] == 2
        # recv_many: raw payloads in arrival order, one Python entry.
        ca.send_many([mod.encode_fence(1), mod.encode_fence(2)])
        msgs = cb.recv_many()
        assert [frame_pump.py_decode(m)["msg_id"] for m in msgs] == [1, 2]
    finally:
        a.close()
        b.close()


@needs_native
def test_chan_framing_roundtrip():
    """Framed pump over a socketpair: coalesced batch send, interleaved
    pickle/native payloads, oversized frames, EOF on shutdown."""
    a, b = socket.socketpair()
    ca = frame_pump.wrap_connection(Connection(a))
    cb = frame_pump.wrap_connection(Connection(b))
    assert ca is not None and cb is not None
    # Dict messages ride pickle; raw codec payloads ride native — both
    # arrive through the same recv().
    mod = frame_pump._module()
    native_frame = mod.encode_fence(99)
    ca.send({"type": "hello", "blob": b"x" * 100})
    ca.send_payloads([native_frame, native_frame])
    assert cb.recv()["type"] == "hello"
    assert cb.recv() == {"type": "fence", "msg_id": 99}
    assert cb.recv() == {"type": "fence", "msg_id": 99}
    # A frame larger than the pump's read buffer still arrives whole.
    big = {"type": "big", "blob": b"z" * (1 << 20)}
    ca.send(big)
    got = cb.recv()
    assert got["blob"] == big["blob"]
    stats = ca.pump_io_stats()
    assert stats["frames_out"] == 4
    ca.close()
    with pytest.raises(ConnectionClosed):
        cb.recv()
    cb.close()
    assert frame_pump.pump_stats()["engaged_channels"] >= 0


def test_rtpu_no_native_knob(monkeypatch):
    """RTPU_NO_NATIVE=1 turns the pump off at every seam: availability,
    wrapping (counted as a 'disabled' fallback), and advertisement."""
    monkeypatch.setenv("RTPU_NO_NATIVE", "1")
    assert not frame_pump.available()
    assert frame_pump.advertised_ver() == 0
    before = frame_pump.pump_stats()["fallbacks"].get("disabled", 0)
    a, b = socket.socketpair()
    try:
        assert frame_pump.wrap_connection(Connection(a)) is None
        assert (frame_pump.pump_stats()["fallbacks"].get("disabled", 0)
                == before + 1)
    finally:
        a.close()
        b.close()


def test_native_metrics_declared():
    """The fallback counter and engaged gauge are registered metric
    surface (tools/check_metric_names.py lints the same names)."""
    from ray_tpu.util.metrics import declared_metrics

    declared = declared_metrics()
    assert declared["ray_tpu_native_fallbacks_total"][0] == "counter"
    assert declared["ray_tpu_native_pump_channels"][0] == "gauge"


def _engage(handle, call):
    from ray_tpu.core.runtime_context import current_runtime

    rt = current_runtime()
    deadline = time.time() + 30
    while time.time() < deadline:
        ray_tpu.get(call(), timeout=30)
        st = rt._direct_states.get(handle.actor_id.binary())
        if st is not None and st["status"] == "ready":
            return st
        time.sleep(0.02)
    raise AssertionError("direct channel never engaged")


@needs_native
def test_direct_plane_rides_native_pump(ray_tpu_start):
    """End to end: the direct channel engages the pump (engaged gauge,
    zero fallbacks), compact args/kwargs/ref-args round-trip through the
    native codec, and a pipelined burst coalesces frames into far fewer
    writev calls."""

    @ray_tpu.remote
    class A:
        def ping(self):
            return b"ok"

        def add(self, x, y=1):
            return x + y

    a = A.remote()
    st = _engage(a, lambda: a.ping.remote())
    chan = st["chan"]
    assert chan.native, "pump did not engage on a plain local channel"
    assert ray_tpu.get(a.add.remote(41)) == 42
    assert ray_tpu.get(a.add.remote(40, y=2)) == 42
    ref = ray_tpu.put(5)
    assert ray_tpu.get(a.add.remote(ref, y=3)) == 8
    before = chan.conn.pump_io_stats()
    refs = [a.ping.remote() for _ in range(256)]
    assert all(v == b"ok" for v in ray_tpu.get(refs, timeout=60))
    after = chan.conn.pump_io_stats()
    frames = after["frames_out"] - before["frames_out"]
    writes = after["write_syscalls"] - before["write_syscalls"]
    assert frames >= 256
    assert writes < frames / 2, (
        f"burst did not coalesce: {frames} frames in {writes} writes"
    )
    stats = frame_pump.pump_stats()
    assert stats["engaged_channels"] >= 1
    assert stats["fallbacks"].get("pump_error", 0) == 0
    assert stats["fallbacks"].get("codec_error", 0) == 0


@needs_native
def test_ordered_replay_with_pump_engaged(ray_tpu_start):
    """Kill the native channel's socket mid-pipeline: unanswered calls
    replay over the NM route in submission order, execute exactly once
    (worker-side task-id dedup), and the channel re-engages natively."""

    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def inc(self):
            self.n += 1
            return self.n

    c = Counter.remote()
    st = _engage(c, lambda: c.inc.remote())
    assert st["chan"].native
    base = ray_tpu.get(c.inc.remote(), timeout=30)
    refs = [c.inc.remote() for _ in range(10)]
    st["chan"].conn.close()
    refs += [c.inc.remote() for _ in range(10)]
    vals = ray_tpu.get(refs, timeout=60)
    assert vals == list(range(base + 1, base + 21))
    st2 = _engage(c, lambda: c.inc.remote())
    assert st2["chan"].native, "did not re-engage the pump after failover"


@needs_native
def test_chaos_direct_channel_io_fires_through_pump(ray_tpu_start):
    """The direct_channel_io chaos point still severs a pump-engaged
    channel (the injection fires in the flush path BEFORE the native
    send), and the exactly-once NM replay holds."""
    from ray_tpu.util import faults

    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def inc(self):
            self.n += 1
            return self.n

    c = Counter.remote()
    st = _engage(c, lambda: c.inc.remote())
    assert st["chan"].native
    base = ray_tpu.get(c.inc.remote(), timeout=30)
    try:
        faults.apply_plan([{"point": "direct_channel_io", "mode": "once"}])
        refs = [c.inc.remote() for _ in range(30)]
        vals = ray_tpu.get(refs, timeout=60)
        assert vals == list(range(base + 1, base + 31))
        assert faults.fired_counts().get("direct_channel_io") == 1
    finally:
        faults.apply_plan([])
    st2 = _engage(c, lambda: c.inc.remote())
    assert st2["chan"].native


@pytest.mark.parametrize("suite", ["tests/test_actor_direct.py"])
@pytest.mark.slow
def test_forced_fallback_runs_direct_suite_pure_python(suite):
    """RTPU_NO_NATIVE=1 must leave the whole direct-plane suite green on
    the pure-Python path — the fallback is a first-class mode, not a
    degraded one."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["RTPU_NO_NATIVE"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", suite, "-q", "-p",
         "no:cacheprovider"],
        cwd=repo,
        env=env,
        capture_output=True,
        timeout=420,
        text=True,
    )
    assert proc.returncode == 0, (
        f"direct-plane suite failed under RTPU_NO_NATIVE=1:\n"
        f"{proc.stdout[-4000:]}\n{proc.stderr[-2000:]}"
    )
