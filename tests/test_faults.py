"""Chaos plane (util/faults.py + GCS ChaosService) and the drain-based
rolling replacement it validates.

The partition matrix armes ONE injection point at a time and asserts the
advertised degradation path with exactly-once semantics: data plane
blocked -> pull falls back to control-plane chunks; direct actor plane
blocked -> calls replay via the NM exactly once; heartbeat blocked ->
the GCS declares the node dead, lineage re-executes, and the node heals
when the plan is disarmed. The rolling-restart test is ROADMAP item 5's
acceptance bar: every worker node of a live cluster is drained and
replaced, one at a time, while a serve deployment keeps answering with
zero failed requests (the head hosts the GCS and is the one node the
drain RPC refuses by design — reference parity: kuberay rolls workers)."""

import threading
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.util import faults
from ray_tpu.util.backoff import Backoff


# --------------------------------------------------------------- unit: specs


def test_validate_spec_rejects_unknowns():
    with pytest.raises(ValueError):
        faults.validate_spec({"point": "not_a_point"})
    with pytest.raises(ValueError):
        faults.validate_spec({"point": "peer_send", "mode": "sometimes"})
    with pytest.raises(ValueError):
        faults.validate_spec({"point": "peer_send", "action": "explode"})
    with pytest.raises(ValueError):  # latency needs a positive delay
        faults.validate_spec({"point": "peer_send", "action": "latency"})
    with pytest.raises(ValueError):
        faults.validate_spec("peer_send")  # not a dict
    out = faults.validate_spec({"point": "heartbeat"})
    assert out["mode"] == "always" and out["action"] == "error"


def test_schedules_are_deterministic():
    """once/every/prob fire on a replayable schedule; max_fires caps;
    clear() disarms back to the free path."""
    try:
        # every 3rd hit
        faults.apply_plan([{"point": "peer_send", "mode": "every", "n": 3}])
        pattern = []
        for _ in range(9):
            try:
                faults.fire(faults.PEER_SEND)
                pattern.append(0)
            except faults.InjectedFault:
                pattern.append(1)
        assert pattern == [0, 0, 1] * 3
        assert faults.fired_counts() == {"peer_send": 3}

        # one-shot on the 2nd hit, then never again
        faults.apply_plan([{"point": "gcs_rpc", "mode": "once", "n": 2}])
        pattern = []
        for _ in range(5):
            try:
                faults.fire(faults.GCS_RPC)
                pattern.append(0)
            except faults.InjectedFault:
                pattern.append(1)
        assert pattern == [0, 1, 0, 0, 0]

        # seeded probabilistic schedule replays identically
        def run():
            faults.apply_plan([{"point": "heartbeat", "mode": "prob",
                                "p": 0.5, "seed": 42}])
            out = []
            for _ in range(32):
                try:
                    faults.fire(faults.HEARTBEAT)
                    out.append(0)
                except faults.InjectedFault:
                    out.append(1)
            return out

        first, second = run(), run()
        assert first == second
        assert 0 < sum(first) < 32

        # max_fires bounds an always schedule
        faults.apply_plan([{"point": "worker_spawn", "mode": "always",
                            "max_fires": 2}])
        fired = 0
        for _ in range(6):
            try:
                faults.fire(faults.WORKER_SPAWN)
            except faults.InjectedFault:
                fired += 1
        assert fired == 2

        # latency action returns the delay instead of raising
        faults.apply_plan([{"point": "peer_send", "action": "latency",
                            "delay_s": 0.25}])
        assert faults.fire(faults.PEER_SEND) == 0.25
    finally:
        faults.clear()
    assert not faults.armed()
    assert faults.fire(faults.PEER_SEND) == 0.0  # disarmed: free no-op


def test_partition_is_sticky_until_disarm():
    """partition != error: after the scheduled first fire, EVERY
    subsequent matching hit fails — without consuming mode counters —
    until the plan is disarmed (a cut cable stays cut)."""
    try:
        # mode=once error: exactly one failure, then clean.
        faults.apply_plan([{"point": "peer_send", "mode": "once",
                            "n": 2, "action": "error"}])
        pattern = []
        for _ in range(6):
            try:
                faults.fire(faults.PEER_SEND)
                pattern.append(0)
            except faults.InjectedFault:
                pattern.append(1)
        assert pattern == [0, 1, 0, 0, 0, 0]

        # mode=once partition: fires at hit 2 and STAYS down.
        faults.apply_plan([{"point": "peer_send", "mode": "once",
                            "n": 2, "action": "partition"}])
        pattern = []
        for _ in range(6):
            try:
                faults.fire(faults.PEER_SEND)
                pattern.append(0)
            except faults.InjectedFault:
                pattern.append(1)
        assert pattern == [0, 1, 1, 1, 1, 1]
        # Sticky refires did not consume the schedule: one real fire.
        assert faults.fired_counts() == {"peer_send": 1}

        # Disarm heals; re-arming the same spec starts clean.
        faults.clear()
        assert faults.fire(faults.PEER_SEND) == 0.0
    finally:
        faults.clear()


def test_partition_sticky_is_scoped_to_matched_context():
    """The sticky state covers exactly the spec's (point, match)
    scope: cutting the link to one peer leaves other peers' traffic
    flowing, and every hit inside the scope fails once cut."""
    try:
        faults.apply_plan([{"point": "peer_send", "mode": "once",
                            "action": "partition",
                            "match": {"peer": "aa"}}])
        with pytest.raises(faults.InjectedFault):
            faults.fire(faults.PEER_SEND, peer="aabbccdd")
        # Same matched context: sticky.
        with pytest.raises(faults.InjectedFault):
            faults.fire(faults.PEER_SEND, peer="aabbccdd")
        # Context outside the scope: traffic flows.
        assert faults.fire(faults.PEER_SEND, peer="ffee0011") == 0.0
        # Any context INSIDE the cut scope fails too (the scope IS the
        # partitioned link).
        with pytest.raises(faults.InjectedFault):
            faults.fire(faults.PEER_SEND, peer="aabb9999")
    finally:
        faults.clear()


def test_append_preserves_exhausted_spec_counters():
    """Re-arming a plan that RETAINS a spec (same GCS-stamped id, as
    the CLI's append flow does) keeps that spec's counters: an
    exhausted ``once`` spec must not fire again just because an
    unrelated spec was armed. Id-less local plans (the tests above)
    keep full reset-on-apply determinism."""
    try:
        one_shot = {"point": "gcs_rpc", "mode": "once", "id": "cs1-0"}
        faults.apply_plan([one_shot])
        with pytest.raises(faults.InjectedFault):
            faults.fire(faults.GCS_RPC)
        assert faults.fire(faults.GCS_RPC) == 0.0  # exhausted

        faults.apply_plan([one_shot,
                           {"point": "peer_send", "id": "cs2-1"}])
        assert faults.fire(faults.GCS_RPC) == 0.0  # STILL exhausted
        with pytest.raises(faults.InjectedFault):
            faults.fire(faults.PEER_SEND)  # the new spec is live
    finally:
        faults.clear()


def test_node_filter_scopes_firing():
    try:
        faults.set_local_node("aabbccdd" + "0" * 24)
        faults.apply_plan([{"point": "peer_send", "node": "aabb"}])
        with pytest.raises(faults.InjectedFault):
            faults.fire(faults.PEER_SEND)
        faults.apply_plan([{"point": "peer_send", "node": "ffff"}])
        assert faults.fire(faults.PEER_SEND) == 0.0  # other node's spec
    finally:
        faults.clear()
        faults.set_local_node("")


def test_injected_fault_is_a_connection_error():
    """Call sites catch the same exceptions a real transport raises, so
    the injected fault must BE one (ConnectionError -> OSError)."""
    assert issubclass(faults.InjectedFault, ConnectionError)
    assert issubclass(faults.InjectedFault, OSError)


# ------------------------------------------------------------- unit: backoff


def test_backoff_is_seeded_capped_and_deadlined():
    a = Backoff(base=0.1, factor=2.0, max_delay=1.0, jitter=0.25, seed=7)
    b = Backoff(base=0.1, factor=2.0, max_delay=1.0, jitter=0.25, seed=7)
    seq_a = [a.next_delay() for _ in range(8)]
    seq_b = [b.next_delay() for _ in range(8)]
    assert seq_a == seq_b  # deterministic under seed
    assert all(d <= 1.0 * 1.25 + 1e-9 for d in seq_a)  # capped (+jitter)
    assert seq_a[0] < seq_a[3]  # grows

    a.reset()
    assert a.attempt == 0
    assert a.next_delay() < 0.2  # back at the base

    d = Backoff(base=10.0, deadline_s=0.0)
    assert d.expired
    assert d.sleep() is False  # nothing slept past the deadline
    # next_delay clamps to the remaining budget
    e = Backoff(base=50.0, jitter=0.0, deadline_s=0.05)
    assert e.next_delay() <= 0.05


# ------------------------------------------------- cluster partition matrix

CHUNK = 256 * 1024


@pytest.fixture
def cluster():
    c = Cluster(
        head_resources={"CPU": 2},
        system_config={
            "num_prestart_workers": 1,
            "object_transfer_chunk_bytes": CHUNK,
            # A peer-partitioned forward pops the target from the view;
            # the grace window keeps the requeued task alive until the
            # next cluster_load broadcast heals it (the production
            # analogue is the autoscaler provisioning a replacement).
            "infeasible_grace_s": 2.0,
            "log_to_driver": False,
        },
    )
    c.add_node(num_cpus=1, resources={"gadget": 1})
    yield c
    try:
        _arm([])  # never leak an armed plan into the next test
    except Exception:
        pass
    faults.clear()
    c.shutdown()


def _nm():
    from ray_tpu.core.runtime_context import current_runtime

    return current_runtime()._nm


def _arm(specs):
    nm = _nm()
    return nm.call_sync(nm._gcs.chaos_arm(specs), timeout=30)


def _chaos_events(point, timeout=5.0):
    """CHAOS firings for ``point`` from the head event store, polling
    past the ring's FLUSH_INTERVAL_S publication latency."""
    from ray_tpu.util.state import list_cluster_events

    deadline = time.time() + timeout
    while True:
        evts = [e for e in list_cluster_events(source="CHAOS")
                if (e.get("custom_fields") or {}).get("point") == point]
        if evts or time.time() >= deadline:
            return evts
        time.sleep(0.1)


def test_arm_propagates_cluster_wide_and_lists(cluster):
    """An armed plan reaches remote nodes AND their workers; list shows
    it; disarm clears it everywhere."""
    _arm([{"point": "worker_spawn", "mode": "every", "n": 1000000}])

    @ray_tpu.remote(resources={"gadget": 1})
    def plan_on_remote_worker():
        from ray_tpu.util import faults as f

        return f.current_plan()

    deadline = time.time() + 20
    plan = []
    while time.time() < deadline:
        plan = ray_tpu.get(plan_on_remote_worker.remote(), timeout=30)
        if plan:
            break
        time.sleep(0.1)
    assert plan and plan[0]["point"] == "worker_spawn"

    nm = _nm()
    listed = nm.call_sync(nm._gcs.chaos_list(), timeout=30)
    assert [s["point"] for s in listed["specs"]] == ["worker_spawn"]

    _arm([])
    deadline = time.time() + 20
    while time.time() < deadline:
        if not ray_tpu.get(plan_on_remote_worker.remote(), timeout=30):
            break
        time.sleep(0.1)
    else:
        raise AssertionError("disarm never reached the remote worker")


def test_data_plane_partition_falls_back_to_chunks(cluster):
    """Block ONLY the striped data plane: pulls fall back to the
    control-plane chunk protocol byte-exactly (zero lost), and the
    plane re-engages after disarm."""
    nm = _nm()
    st = nm._transfer.stats
    nbytes = 8 * 1024 * 1024

    @ray_tpu.remote(resources={"gadget": 1})
    def produce():
        rng = np.random.RandomState(3)
        return rng.randint(0, 255, size=nbytes, dtype=np.uint8)

    # Warm: the plane streams.
    got = ray_tpu.get(produce.remote(), timeout=120)
    assert st["ranges_served"] >= 1 or st["striped_pulls"] >= 1, st

    _arm([{"point": "data_channel_io", "mode": "always",
           "action": "partition"}])
    chunks_before = st["chunked_pulls"]
    fallbacks_before = st["fallback_pulls"]
    got = ray_tpu.get(produce.remote(), timeout=120)
    rng = np.random.RandomState(3)
    assert np.array_equal(got, rng.randint(0, 255, size=nbytes,
                                           dtype=np.uint8))
    assert st["chunked_pulls"] > chunks_before, st
    assert st["fallback_pulls"] > fallbacks_before, st
    assert _chaos_events("data_channel_io"), "firing must be observable"

    _arm([])
    striped_before = st["striped_pulls"]
    ray_tpu.get(produce.remote(), timeout=120)
    assert st["striped_pulls"] > striped_before, st  # plane re-engaged


def test_direct_plane_partition_replays_exactly_once(cluster):
    """Sever the direct actor channel via injection: unanswered calls
    replay over the NM route in order, each executes exactly once, and
    the channel re-engages after disarm."""
    from ray_tpu.core import runtime_context

    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def inc(self):
            self.n += 1
            return self.n

    c = Counter.remote()
    runtime = runtime_context.current_runtime()
    key = c.actor_id.binary()
    deadline = time.time() + 20
    while time.time() < deadline:
        ray_tpu.get(c.inc.remote(), timeout=30)
        st = runtime._direct_states.get(key)
        if st is not None and st["status"] == "ready":
            break
        time.sleep(0.05)
    else:
        raise AssertionError("direct channel never engaged")

    base = ray_tpu.get(c.inc.remote(), timeout=30)
    _arm([{"point": "direct_channel_io", "mode": "once"}])
    refs = [c.inc.remote() for _ in range(30)]
    vals = ray_tpu.get(refs, timeout=60)
    # Zero lost, zero duplicated, strict submission order across the
    # injected channel death (worker-side task-id dedup on replay).
    assert vals == list(range(base + 1, base + 31))
    assert _chaos_events("direct_channel_io")

    _arm([])
    cur = ray_tpu.get(c.inc.remote(), timeout=30)
    vals = ray_tpu.get([c.inc.remote() for _ in range(20)], timeout=60)
    assert vals == list(range(cur + 1, cur + 21))


def test_worker_spawn_fault_is_retried(cluster):
    """A suppressed worker spawn releases its slot; the next scheduler
    pass retries and the task completes (zero lost)."""
    _arm([{"point": "worker_spawn", "mode": "once",
           "node": cluster.head_node_id[:8]}])

    # Force a NEW worker on the head: more concurrent tasks than live
    # workers (prestart is 1).
    @ray_tpu.remote
    def busy(i):
        time.sleep(0.3)
        return i

    got = sorted(ray_tpu.get([busy.remote(i) for i in range(3)],
                             timeout=120))
    assert got == [0, 1, 2]


def test_peer_send_fault_requeues_and_respills(cluster):
    """Bounded peer-channel faults: a failed task forward is treated
    like a node death for that record — requeued, re-placed when the
    view heals, and completed (zero lost)."""
    _arm([{"point": "peer_send", "mode": "always", "max_fires": 2,
           "node": cluster.head_node_id[:8]}])

    @ray_tpu.remote(resources={"gadget": 1})
    def on_gadget():
        import ray_tpu as rt

        return rt.get_runtime_context().get_node_id()

    got = ray_tpu.get(on_gadget.remote(), timeout=120)
    assert got != cluster.head_node_id
    assert _chaos_events("peer_send")


def test_gcs_rpc_latency_injection_stays_live(cluster):
    """A slow GCS (latency injection on the node->GCS RPC path) delays
    but never breaks cross-node work; every firing is observable."""
    _arm([{"point": "gcs_rpc", "action": "latency", "delay_s": 0.2,
           "max_fires": 3}])

    @ray_tpu.remote(resources={"gadget": 1})
    def produce():
        return np.ones(1024, dtype=np.int64)

    @ray_tpu.remote  # consumed on the head: locate + pull via the GCS
    def consume(a):
        return int(a.sum())

    assert ray_tpu.get(consume.remote(produce.remote()),
                       timeout=120) == 1024


@pytest.mark.slow
def test_heartbeat_partition_death_lineage_and_heal():
    """Block ONLY a node's heartbeat send: the GCS declares it dead,
    lineage re-executes what it owned (zero lost), and — because only
    the send half is faulted — the node re-registers and heals the
    moment the plan is disarmed."""
    c = Cluster(
        head_resources={"CPU": 2},
        system_config={
            "num_prestart_workers": 0,
            "heartbeat_interval_s": 0.2,
            "gcs_health_check_period_s": 0.2,
            "node_death_timeout_s": 1.5,
            "log_to_driver": False,
        },
    )
    try:
        h = c.add_node(num_cpus=1, resources={"gadget": 1})
        target = h.node_id_hex

        @ray_tpu.remote(resources={"gadget": 1}, max_restarts=2,
                        max_task_retries=2)
        class A:
            def __init__(self):
                self.n = 0

            def bump(self):
                self.n += 1
                return self.n

        a = A.remote()
        assert ray_tpu.get(a.bump.remote(), timeout=60) == 1

        _arm([{"point": "heartbeat", "mode": "always",
               "action": "partition", "node": target}])
        deadline = time.time() + 30
        while time.time() < deadline:
            views = {v["NodeID"]: v["State"] for v in ray_tpu.nodes()}
            if views.get(target) == "dead":
                break
            time.sleep(0.2)
        else:
            raise AssertionError("node never declared dead")

        _arm([])
        deadline = time.time() + 40
        while time.time() < deadline:
            views = {v["NodeID"]: v["State"] for v in ray_tpu.nodes()}
            if views.get(target) == "alive":
                break
            time.sleep(0.2)
        else:
            raise AssertionError(f"node never healed: {views}")

        # The actor restarted via lineage (fresh state, exactly-once per
        # incarnation: strictly increasing values, no duplicates).
        vals = ray_tpu.get([a.bump.remote() for _ in range(5)],
                           timeout=120)
        assert vals == sorted(set(vals)), vals
        assert _chaos_events("heartbeat")
    finally:
        faults.clear()
        c.shutdown()


# ------------------------------------------------ drain & rolling restart


@pytest.mark.slow
def test_drain_node_migrates_objects_and_reports():
    """rtpu drain semantics: primary copies replicate off-node before
    exit, consumers re-locate (no reconstruction), the node leaves the
    cluster cleanly."""
    c = Cluster(
        head_resources={"CPU": 2},
        system_config={"num_prestart_workers": 1,
                       "log_to_driver": False},
    )
    try:
        h = c.add_node(num_cpus=1, resources={"gadget": 1})
        target = h.node_id_hex

        @ray_tpu.remote(resources={"gadget": 1})
        def produce():
            return np.arange(300_000, dtype=np.int64)

        ref = produce.remote()
        assert int(ray_tpu.get(ref, timeout=60)[-1]) == 299_999
        # Drop the local cached copy path: the driver re-pulls below.

        report = ray_tpu.drain_node(target, timeout=60)
        assert report["ok"], report
        assert report["replicated"] >= 1, report

        deadline = time.time() + 30
        while time.time() < deadline:
            views = {v["NodeID"]: v["State"] for v in ray_tpu.nodes()}
            if views.get(target) == "dead":
                break
            time.sleep(0.2)
        else:
            raise AssertionError("drained node never exited")

        # The replicated copy answers without lineage re-execution.
        assert int(ray_tpu.get(ref, timeout=60)[12345]) == 12345
        with pytest.raises((ValueError, RuntimeError)):
            ray_tpu.drain_node(c.head_node_id)  # head refuses by design
    finally:
        c.shutdown()


def test_drain_abort_returns_node_to_service():
    """A failed drain must not strand the node in 'draining' (reachable
    but unschedulable forever): the abort phase rolls it back to alive
    and the schedulers target it again."""
    c = Cluster(
        head_resources={"CPU": 2},
        system_config={"num_prestart_workers": 1,
                       "log_to_driver": False},
    )
    try:
        h = c.add_node(num_cpus=1, resources={"gadget": 1})
        target = h.node_id_hex
        nm = _nm()

        reply = nm.call_sync(
            nm._gcs.drain_node(target, phase="begin"), timeout=30)
        assert reply["ok"], reply
        views = {v["NodeID"]: v["State"] for v in ray_tpu.nodes()}
        assert views[target] == "draining"

        reply = nm.call_sync(
            nm._gcs.drain_node(target, phase="abort"), timeout=30)
        assert reply["ok"], reply
        deadline = time.time() + 10
        while time.time() < deadline:
            views = {v["NodeID"]: v["State"] for v in ray_tpu.nodes()}
            if views.get(target) == "alive":
                break
            time.sleep(0.1)
        else:
            raise AssertionError(
                f"node stayed {views.get(target)!r} after drain abort"
            )

        # Schedulable again: only the un-drained node has this resource.
        @ray_tpu.remote(resources={"gadget": 1})
        def probe():
            return "ok"

        assert ray_tpu.get(probe.remote(), timeout=60) == "ok"
    finally:
        c.shutdown()


@pytest.mark.slow
def test_rolling_restart_keeps_serve_answering():
    """ROADMAP item 5 acceptance: every worker node of a live 3-node
    cluster is drained and replaced one at a time while a serve
    deployment keeps answering — zero failed requests end to end."""
    from ray_tpu import serve

    c = Cluster(
        head_resources={"CPU": 2},
        system_config={"num_prestart_workers": 1,
                       "log_to_driver": False},
    )
    try:
        c.add_node(num_cpus=2)
        c.add_node(num_cpus=2)
        c.wait_for_nodes(3)
        old_nodes = {v["NodeID"] for v in ray_tpu.nodes()}

        @serve.deployment(num_replicas=2)
        class Echo:
            def __call__(self, x):
                return {"echo": x}

        handle = serve.run(Echo.bind(), name="chaos-echo")
        assert handle.remote(1).result(timeout=60) == {"echo": 1}

        failures = []
        answered = [0]
        stop = threading.Event()

        def hammer():
            i = 0
            while not stop.is_set():
                try:
                    out = handle.remote(i).result(timeout=30)
                    assert out == {"echo": i}
                    answered[0] += 1
                except Exception as e:  # noqa: BLE001 — recorded, asserted
                    failures.append(repr(e))
                i += 1
                time.sleep(0.02)

        t = threading.Thread(target=hammer, daemon=True)
        t.start()
        try:
            replaced = c.rolling_restart(drain_timeout=60)
        finally:
            time.sleep(1.0)
            stop.set()
            t.join(timeout=30)

        assert len(replaced) == 2, replaced
        for old_hex, new_hex in replaced:
            assert old_hex != new_hex
        assert not failures, failures[:5]
        assert answered[0] > 50, answered  # live the whole time

        views = {v["NodeID"]: v["State"] for v in ray_tpu.nodes()}
        alive = {n for n, s in views.items() if s == "alive"}
        assert len(alive) == 3, views
        for old_hex, _ in replaced:
            assert old_hex not in alive
        # Replaced cluster still serves.
        assert handle.remote(99).result(timeout=60) == {"echo": 99}
    finally:
        try:
            serve.shutdown()
        except Exception:
            pass
        c.shutdown()
