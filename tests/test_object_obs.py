"""Data-plane observability (util/data_obs.py + the GCS ObjectService):
cluster object census, leak detection, transfer-stall watchdogs, and the
per-link bandwidth matrix.

Acceptance bars exercised here (ISSUE: data-plane observability):
  - census fan-out degrades to a PARTIAL reply when a node dies (never
    a hang), and rows carry state/owner/age enrichment;
  - the head leak sweep flags an orphaned object within
    ``object_leak_warn_s`` with exactly ONE deduped WARNING, and the
    leak gauges clear on GC;
  - a chaos-stalled pull raises the LIVE stalled{peer} gauge WHILE the
    pull is stuck, emits one deduped WARNING, drops a flight-recorder
    record (reason ``stalled_pull``) joinable by the pull's oid, and
    the gauge returns to zero on recovery;
  - pulled bytes land in the (src,dst) link-bandwidth matrix;
  - a mid-pull data-channel death leaves every inflight gauge at zero
    (satellite: object_transfer error-path accounting audit);
  - ``RTPU_NO_DATA_OBS=1`` turns the whole plane into a no-op.
"""

import threading
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.util import faults

CHUNK = 256 * 1024  # shrink chunks so 1 MiB objects stripe

STALL_WARN_S = 0.5
STALL_DELAY_S = 4.0


@pytest.fixture
def cluster():
    c = Cluster(
        head_resources={"CPU": 2},
        system_config={
            "num_prestart_workers": 1,
            "default_max_retries": 0,
            "object_transfer_chunk_bytes": CHUNK,
            "transfer_stall_warn_s": STALL_WARN_S,
            "object_leak_warn_s": 1.0,
            # GC must not race the leak sweep: zero-ref entries stay
            # put so the sweep (not the collector) decides their fate.
            "gc_grace_period_s": 600.0,
            "log_to_driver": False,
        },
    )
    c.add_node(num_cpus=1, resources={"gadget": 1})
    yield c
    try:
        _arm([])
    except Exception:
        pass
    faults.clear()
    c.shutdown()


def _nm():
    from ray_tpu.core.runtime_context import current_runtime

    return current_runtime()._nm


def _rt():
    from ray_tpu.core.runtime_context import current_runtime

    return current_runtime()


def _arm(specs):
    nm = _nm()
    return nm.call_sync(nm._gcs.chaos_arm(specs), timeout=30)


def _poll(fn, timeout=15.0, interval=0.05):
    """Poll ``fn`` until truthy or timeout; returns the last value."""
    deadline = time.monotonic() + timeout
    val = fn()
    while not val and time.monotonic() < deadline:
        time.sleep(interval)
        val = fn()
    return val


def _series(name):
    """This process's live series for one metric: {tags_key: value}.
    Reads the in-process registry directly — the head NM shares the
    test process, so data-plane gauges are visible without the KV
    pipeline's flush latency."""
    from ray_tpu.util.metrics import _registry

    with _registry.lock:
        _kind, series = _registry.metrics.get(name, ("", {}))
        return dict(series)


def _object_events(substr, timeout=0.0):
    """OBJECT_STORE WARNINGs whose message contains ``substr``."""
    from ray_tpu.util.state import list_cluster_events

    def fetch():
        return [e for e in list_cluster_events(source="OBJECT_STORE")
                if e.get("severity") == "WARNING"
                and substr in (e.get("message") or "")]

    if timeout:
        return _poll(fetch, timeout=timeout)
    return fetch()


# ------------------------------------------------------------------ census


def test_census_rows_states_owners_and_totals(cluster):
    """cluster_objects merges every node's index with lifecycle state,
    producer owner, and store totals."""
    ref = ray_tpu.put(np.zeros(1 << 20, dtype=np.uint8))

    @ray_tpu.remote(resources={"gadget": 0.1})
    def make():
        return b"x" * 4096

    got = ray_tpu.get(make.remote())
    assert got == b"x" * 4096
    census = _rt().cluster_objects(limit=100)
    assert census["errors"] == {}
    assert len(census["nodes"]) == 2
    rows = [r for n in census["nodes"] for r in n["objects"]]
    owners = {r["owner"] for r in rows}
    assert "put" in owners and "make" in owners
    assert all(r["state"] for r in rows)
    assert any(r["state"] == "in-memory" and r["size_bytes"] >= (1 << 20)
               for r in rows)
    # Age enrichment live while the plane is on.
    assert all(r["age_s"] is not None for r in rows)
    head = next(n for n in census["nodes"] if n["is_head"])
    assert head["used_bytes"] >= (1 << 20)
    assert head["capacity_bytes"] >= 0
    del ref


def test_census_partial_when_node_dies(cluster):
    """A dead node degrades the census to a partial reply — its hex in
    ``errors`` or gone from ``nodes`` — instead of hanging the call."""

    @ray_tpu.remote(resources={"gadget": 0.1})
    def touch():
        return 1

    assert ray_tpu.get(touch.remote()) == 1
    assert len(_rt().cluster_objects(limit=10)["nodes"]) == 2
    cluster.remove_node(cluster._nodes[0])
    t0 = time.monotonic()
    census = _rt().cluster_objects(limit=10)
    assert time.monotonic() - t0 < 25.0  # partial, never a hang
    head_rows = [n for n in census["nodes"] if n["is_head"]]
    assert len(head_rows) == 1
    # The dead node either already left the alive set or landed in
    # errors — both are partial results, not a hang.
    assert len(census["nodes"]) == 1 or census["errors"]


# ---------------------------------------------------------- leak detection


def test_leak_detector_fires_once_and_clears_on_gc(cluster):
    """An orphaned sealed object (zero refs, nobody collecting it) is
    flagged within object_leak_warn_s: leak gauges rise, exactly one
    WARNING fires, and GC clears the gauges."""
    from ray_tpu.core.ids import ObjectID
    from ray_tpu.core.object_store import InlineLocation

    nm = _nm()
    oid = ObjectID.from_random()
    nm.directory.add(oid, InlineLocation(b"z" * 2048), initial_refs=0,
                     owner="orphan")

    evts = _object_events("LEAK suspected", timeout=15.0)
    assert evts, "leak sweep never flagged the orphan"
    assert any((e.get("custom_fields") or {}).get("object_id")
               == oid.hex() for e in evts)
    leaked = _poll(lambda: [v for v in
                            _series("ray_tpu_object_leaked_total")
                            .values() if v], timeout=10.0)
    assert leaked and max(leaked) >= 1
    bytes_vals = _series("ray_tpu_object_leaked_bytes").values()
    assert max(bytes_vals) >= 2048

    # Deduped: two more sweep periods must not re-warn the same oid.
    time.sleep(1.5)
    n_before = len([e for e in _object_events("LEAK suspected")
                    if (e.get("custom_fields") or {}).get("object_id")
                    == oid.hex()])
    assert n_before == 1

    # GC the orphan: the next sweep publishes zero.
    nm.directory.collect_garbage(0.0)
    cleared = _poll(
        lambda: all(v == 0 for v in
                    _series("ray_tpu_object_leaked_total").values()),
        timeout=10.0,
    )
    assert cleared, _series("ray_tpu_object_leaked_total")


# ------------------------------------------------------------ stall watchdog


def test_stalled_pull_live_gauge_warning_and_flight_record(cluster):
    """Sticky data-channel latency stalls a pull: the stalled{peer}
    gauge is nonzero WHILE the pull is stuck, exactly one WARNING
    fires, a flight-recorder record (reason stalled_pull) joins by the
    pull's oid, and the gauge returns to zero after recovery."""
    nbytes = 1 << 20

    @ray_tpu.remote(resources={"gadget": 1})
    def produce():
        return np.ones(nbytes, dtype=np.uint8)

    _arm([{"point": "data_channel_io", "mode": "always",
           "action": "latency", "delay_s": STALL_DELAY_S}])

    result = {}

    def puller():
        result["data"] = ray_tpu.get(produce.remote(), timeout=120)

    th = threading.Thread(target=puller)
    th.start()
    try:
        # LIVE while stuck: the gauge must rise before the pull ends.
        stalled = _poll(
            lambda: (not th.is_alive() or
                     any(v >= 1 for v in
                         _series("ray_tpu_object_transfer_stalled")
                         .values())),
            timeout=STALL_DELAY_S + 20.0,
        )
        assert stalled
        assert th.is_alive(), \
            "pull finished before the watchdog could be observed"
        assert any(v >= 1 for v in
                   _series("ray_tpu_object_transfer_stalled").values())
        # The census inflight table shows the same stall.
        pulls = _nm()._transfer.inflight_pulls()
        assert pulls and any(p["stalled"] for p in pulls)
    finally:
        th.join(timeout=120)
    assert result["data"].nbytes == nbytes  # recovered, byte-exact

    evts = _object_events("TRANSFER stalled", timeout=15.0)
    assert len(evts) == 1, [e.get("message") for e in evts]
    oid_hex = (evts[0].get("custom_fields") or {}).get("object_id")
    assert oid_hex

    from ray_tpu.util import flight_recorder

    recs = _poll(lambda: flight_recorder.list_cluster(
        reason="stalled_pull", limit=50), timeout=10.0)
    assert recs, "no stalled_pull flight-recorder record"
    rec = next(r for r in recs if oid_hex[:8] in r["name"])
    assert rec["trace_id"] == oid_hex[:32]  # joinable via `rtpu trace`
    assert "peer=" in (rec.get("detail") or "")

    _arm([])
    cleared = _poll(
        lambda: all(v == 0 for v in
                    _series("ray_tpu_object_transfer_stalled")
                    .values()),
        timeout=10.0,
    )
    assert cleared, _series("ray_tpu_object_transfer_stalled")
    assert _nm()._transfer.inflight_pulls() == []


# ------------------------------------------------------- bandwidth matrix


def test_link_bandwidth_matrix_accounts_pulled_bytes(cluster):
    """A cross-node pull lands its payload in the directed (src,dst)
    link counter feeding `rtpu transfers`."""
    nbytes = 1 << 20

    @ray_tpu.remote(resources={"gadget": 1})
    def produce():
        return np.full(nbytes, 7, dtype=np.uint8)

    got = ray_tpu.get(produce.remote(), timeout=120)
    assert got.nbytes == nbytes
    nm = _nm()
    dst = nm.node_id.hex()[:8]
    series = _series("ray_tpu_transfer_link_bytes_total")
    moved = {}
    for tags_key, val in series.items():
        tags = dict(tags_key)
        moved[(tags.get("src"), tags.get("dst"))] = val
    into_head = {k: v for k, v in moved.items() if k[1] == dst}
    assert into_head, f"no link series toward {dst}: {moved}"
    assert sum(into_head.values()) >= nbytes


# --------------------------------------- satellite: error-path accounting


def test_channel_death_mid_pull_releases_inflight_gauges(cluster):
    """Killing the striped data plane mid-pull (partition injection)
    falls back to control-plane chunks AND leaves every inflight meter
    at zero — no leaked _set_inflight/_inflight_bytes bookkeeping."""
    nbytes = 1 << 20

    @ray_tpu.remote(resources={"gadget": 1})
    def produce():
        rng = np.random.RandomState(7)
        return rng.randint(0, 255, size=nbytes, dtype=np.uint8)

    nm = _nm()
    st = nm._transfer.stats
    fallbacks_before = st["fallback_pulls"]
    _arm([{"point": "data_channel_io", "mode": "always",
           "action": "partition"}])
    got = ray_tpu.get(produce.remote(), timeout=120)
    rng = np.random.RandomState(7)
    assert np.array_equal(got, rng.randint(0, 255, size=nbytes,
                                           dtype=np.uint8))
    assert st["fallback_pulls"] > fallbacks_before, st

    assert nm._transfer._inflight_bytes == 0
    assert nm._transfer.inflight_by_peer() == {}
    assert nm._transfer.inflight_pulls() == []
    # The per-peer inflight gauge series all ended back at zero.
    assert all(v == 0 for v in
               _series("ray_tpu_object_transfer_inflight").values())


# ------------------------------------------------------------- kill switch


def test_no_data_obs_env_disables_the_plane():
    """RTPU_NO_DATA_OBS=1: factories return None, publishes no-op (no
    series materialize), census rows degrade to age-less/owner-less."""
    import os
    import subprocess
    import sys

    script = r"""
import ray_tpu
from ray_tpu.util import data_obs

assert data_obs.ENABLED is False
assert data_obs.pull_tracker() is None
data_obs.record_link_bytes("a", "b", 123, flush=True)
data_obs.record_spill("spill", 456)
data_obs.set_stalled("p", 3)
data_obs.set_leaked(1, 2)
from ray_tpu.util.metrics import _registry
for name in ("ray_tpu_transfer_link_bytes_total",
             "ray_tpu_object_transfer_stalled",
             "ray_tpu_object_leaked_total",
             "ray_tpu_spill_ops_total"):
    assert name not in _registry.metrics, name

from ray_tpu.cluster_utils import Cluster

c = Cluster(head_resources={"CPU": 1},
            system_config={"log_to_driver": False})
try:
    ref = ray_tpu.put(b"x" * 100_000)
    from ray_tpu.core.runtime_context import current_runtime

    nm = current_runtime()._nm
    assert nm._transfer is None or nm._transfer._tracker is None
    census = current_runtime().cluster_objects(limit=10)
    rows = [r for n in census["nodes"] for r in n["objects"]]
    assert rows
    assert all(r["created_ts"] is None and r["age_s"] is None
               and r["owner"] == "" for r in rows)
finally:
    c.shutdown()
print("NOOP_OK")
"""
    env = dict(os.environ)
    env["RTPU_NO_DATA_OBS"] = "1"
    env.setdefault("JAX_PLATFORMS", "cpu")
    out = subprocess.run(
        [sys.executable, "-c", script], env=env, capture_output=True,
        text=True, timeout=180,
    )
    assert out.returncode == 0, out.stderr[-4000:]
    assert "NOOP_OK" in out.stdout
