"""Control-plane hardening tests: GCS snapshot/restore across head
restarts and a chaos fixture randomly killing workers/nodes under load
(ref analogue: the reference's GCS FT tests + _private/test_utils.py:1391
get_and_run_resource_killer)."""

import random
import threading
import time

import pytest

import ray_tpu


def test_gcs_snapshot_restores_kv_functions_named_actors(tmp_path):
    """Head restart with gcs_storage_path keeps the KV, the function
    table, and the named-actor registry (ref: gcs_storage FT)."""
    storage = str(tmp_path / "gcs.snapshot")

    rt = ray_tpu.init(
        num_cpus=2, system_config={"gcs_storage_path": storage,
                                   "heartbeat_interval_s": 0.1},
    )

    @ray_tpu.remote
    class Named:
        def who(self):
            return "named"

    a = Named.options(name="survivor").remote()
    assert ray_tpu.get(a.who.remote()) == "named"
    ray_tpu.kv_put("durable-key", b"durable-value")

    @ray_tpu.remote
    def registered(x):
        return x + 1

    assert ray_tpu.get(registered.remote(1)) == 2
    # Let the snapshot loop flush, then take the head down.
    deadline = time.monotonic() + 10
    import os

    while time.monotonic() < deadline and not os.path.exists(storage):
        time.sleep(0.1)
    ray_tpu.shutdown()
    assert os.path.exists(storage)

    # "Restarted" head: a fresh GCS restoring from the same storage path.
    ray_tpu.init(
        num_cpus=2, system_config={"gcs_storage_path": storage,
                                   "heartbeat_interval_s": 0.1},
    )
    try:
        assert ray_tpu.kv_get("durable-key") == b"durable-value"
        # The named-actor registry survived: the name is still claimed
        # (its node is gone, so calls fail, but the registration — what
        # the GCS owns — was not lost).
        from ray_tpu.core.runtime_context import current_runtime

        gcs = current_runtime()._nm.gcs_service
        assert "survivor" in gcs._named_actors
        # Function table survived too.
        assert len(gcs._functions) >= 1
    finally:
        ray_tpu.shutdown()



def test_chaos_worker_killer_under_load(ray_tpu_start):
    """Randomly SIGKILL worker processes while retriable tasks run: every
    task must still complete with the right answer (ref analogue:
    WorkerKillerActor chaos tests)."""
    import os
    import signal

    stop = threading.Event()
    killed = [0]

    def killer():
        rng = random.Random(0)
        from ray_tpu.core.runtime_context import current_runtime

        nm = current_runtime()._nm
        while not stop.is_set():
            time.sleep(rng.uniform(0.2, 0.5))
            workers = [w for w in list(nm._workers.values())
                       if w.proc is not None and w.state in
                       ("busy", "idle")]
            if workers:
                victim = rng.choice(workers)
                try:
                    os.kill(victim.proc.pid, signal.SIGKILL)
                    killed[0] += 1
                except OSError:
                    pass

    # Retry budget sized for a LOADED box: slow attempts widen each
    # task's kill-exposure window, and with 120 tasks a 5-retry budget
    # makes P(some task eats 6 consecutive kills) non-negligible —
    # observed as a rare in-suite flake. 12 retries keeps the chaos
    # semantics (every task survives worker murder) with ~1e-5 tails.
    @ray_tpu.remote(max_retries=12)
    def work(i):
        time.sleep(0.05)
        return i * i

    t = threading.Thread(target=killer, daemon=True)
    t.start()
    try:
        refs = [work.remote(i) for i in range(120)]
        results = ray_tpu.get(refs, timeout=120)
    finally:
        stop.set()
        t.join(timeout=5)
    assert results == [i * i for i in range(120)]
    assert killed[0] >= 1, "chaos killer never fired"
