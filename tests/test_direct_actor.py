"""Direct actor-call transport (runtime._DirectChannel + worker_main
_direct_serve): same-node callers bypass the node manager for actor
method calls; replies return inline. Ref analogue:
core_worker/transport/direct_actor_task_submitter.h."""

import time

import pytest

import ray_tpu


@pytest.fixture
def rt():
    ray_tpu.init(num_cpus=2, system_config={"log_to_driver": False})
    yield
    ray_tpu.shutdown()


def _direct_states(runtime=None):
    from ray_tpu.core import runtime_context

    rt = runtime or runtime_context.current_runtime()
    return rt._direct_states


def test_ordering_across_switchover(rt):
    """Calls issued before and after the NM→direct switchover observe
    strict submission order (the discovery only completes once the NM
    queue for the actor drained)."""

    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def inc(self):
            self.n += 1
            return self.n

    c = Counter.remote()
    vals = ray_tpu.get([c.inc.remote() for _ in range(300)])
    assert vals == list(range(1, 301))


def test_direct_channel_engages(rt):
    @ray_tpu.remote
    class A:
        def ping(self):
            return b"ok"

    a = A.remote()
    ray_tpu.get(a.ping.remote())
    deadline = time.time() + 10
    st = None
    while time.time() < deadline:
        ray_tpu.get(a.ping.remote())
        states = _direct_states()
        st = states.get(a.actor_id.binary())
        if st is not None and st["status"] == "ready":
            break
        time.sleep(0.05)
    assert st is not None and st["status"] == "ready", st


def test_ref_args_and_result_reuse(rt):
    """Object args resolve through the worker; direct results are
    registered with the NM so non-caller consumers can read them."""

    @ray_tpu.remote
    class Echo:
        def echo(self, x):
            return x * 2

    @ray_tpu.remote
    def consume(x):
        return x + 1

    e = Echo.remote()
    ray_tpu.get(e.echo.remote(1))  # switch to direct
    ref = ray_tpu.put(21)
    out = e.echo.remote(ref)       # ref arg over the direct channel
    assert ray_tpu.get(consume.remote(out)) == 43  # result feeds a task


def test_kill_fails_pending_direct_calls(rt):
    from ray_tpu.core.exceptions import ActorDiedError, TaskError

    @ray_tpu.remote
    class Slow:
        def ping(self):
            return b"ok"

        def nap(self, s):
            time.sleep(s)
            return "done"

    s = Slow.remote()
    for _ in range(3):
        ray_tpu.get(s.ping.remote())  # ensure direct channel is live
    ref = s.nap.remote(30)
    time.sleep(0.2)
    ray_tpu.kill(s)
    with pytest.raises((ActorDiedError, TaskError)):
        ray_tpu.get(ref, timeout=10)


def test_streaming_call_fences_direct_traffic(rt):
    """A streaming (NM-routed) call interleaved with direct calls must
    not overtake them: the submit path fences the direct channel and
    tears it down until the NM queue drains again."""

    @ray_tpu.remote
    class Gen:
        def __init__(self):
            self.n = 0

        def bump(self):
            self.n += 1
            return self.n

        def stream(self, k):
            for i in range(k):
                yield (self.n, i)

    g = Gen.remote()
    for _ in range(5):
        ray_tpu.get(g.bump.remote())  # direct channel live
    # burst of direct calls, then immediately a streaming call: the
    # generator must observe all 10 bumps.
    for _ in range(5):
        g.bump.remote()
    items = [ray_tpu.get(r) for r in
             g.stream.options(num_returns="streaming").remote(3)]
    assert [i for _, i in items] == [0, 1, 2]
    assert items[0][0] == 10
    # and afterwards order still holds
    assert ray_tpu.get(g.bump.remote()) == 11


def test_concurrent_actor_pool_direct(rt):
    """max_concurrency actors serve direct calls via the pool."""

    @ray_tpu.remote(max_concurrency=4)
    class Pooled:
        def __init__(self):
            import threading

            # All four calls must be IN FLIGHT at once to pass the
            # barrier; serial execution breaks it (no wall clock).
            self.barrier = threading.Barrier(4)

        def rendezvous(self):
            self.barrier.wait(timeout=30)
            return "x"

    p = Pooled.remote()
    out = ray_tpu.get([p.rendezvous.remote() for _ in range(4)],
                      timeout=60)
    assert out == ["x"] * 4


def test_named_actor_from_second_handle(rt):
    """A handle recreated by name reaches the same direct actor."""

    @ray_tpu.remote(name="direct-named")
    class N:
        def __init__(self):
            self.v = 0

        def setv(self, v):
            self.v = v
            return self.v

        def getv(self):
            return self.v

    n = N.remote()
    ray_tpu.get(n.setv.remote(7))
    h = ray_tpu.get_actor("direct-named")
    assert ray_tpu.get(h.getv.remote()) == 7


def test_chained_pending_direct_result(rt):
    """A call whose argument is a still-pending direct result routes via
    the NM (dep-gated) instead of riding the channel — the worker would
    otherwise execute it while the dependency's seal sits in a reply
    batch (review finding: chained-call deadlock)."""

    @ray_tpu.remote
    class Chain:
        def f(self):
            return 10

        def g(self, x):
            return x + 5

    c = Chain.remote()
    for _ in range(3):
        ray_tpu.get(c.f.remote())  # engage the direct channel
    r1 = c.f.remote()
    r2 = c.g.remote(r1)
    assert ray_tpu.get(r2, timeout=30) == 15
    # and a longer chain
    r = c.f.remote()
    for _ in range(5):
        r = c.g.remote(r)
    assert ray_tpu.get(r, timeout=30) == 35
