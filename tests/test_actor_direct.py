"""Direct actor-call plane: fallback discipline + cross-runtime riders
(runtime._DirectChannel <-> worker_main._direct_serve, ISSUE 5).

Covers what tests/test_direct_actor.py (the happy-path suite) does not:
channel death mid-call -> NM-path replay preserving per-handle call
ordering with exactly-once method execution; actor restart re-resolving
the endpoint; serve handles and worker-runtime callers riding the same
plane; out-of-order sequence frames buffered by the worker; and the
PeerClient.close() fast-fail regression."""

import threading
import time

import pytest

import ray_tpu


@pytest.fixture
def rt():
    ray_tpu.init(num_cpus=2, system_config={"log_to_driver": False})
    yield
    ray_tpu.shutdown()


def _runtime():
    from ray_tpu.core import runtime_context

    return runtime_context.current_runtime()


def _engage(handle, call, deadline_s=15.0):
    """Drive calls until the handle's direct channel is ready; returns
    the state dict."""
    deadline = time.time() + deadline_s
    st = None
    while time.time() < deadline:
        ray_tpu.get(call())
        st = _runtime()._direct_states.get(handle.actor_id.binary())
        if st is not None and st["status"] == "ready":
            return st
        time.sleep(0.02)
    raise AssertionError(f"direct channel never engaged: {st}")


def test_channel_death_replays_in_order(rt):
    """Injected channel death mid-burst: unanswered calls replay over
    the NM path IN ORDER, later calls queue behind them, every call
    executes exactly once, and the channel re-engages afterwards with
    no steady-state fallbacks."""

    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def inc(self):
            self.n += 1
            return self.n

    c = Counter.remote()
    st = _engage(c, lambda: c.inc.remote())
    runtime = _runtime()
    base = ray_tpu.get(c.inc.remote())
    fallbacks_before = runtime._direct_fallbacks

    refs = [c.inc.remote() for _ in range(20)]
    st["chan"].conn.close()  # injected fault: kill the raw socket
    refs += [c.inc.remote() for _ in range(20)]
    vals = ray_tpu.get(refs, timeout=60)
    # Strict submission order AND exactly-once execution across the
    # failover (the worker dedups replayed task ids it already ran).
    assert vals == list(range(base + 1, base + 41))
    assert runtime._direct_fallbacks > fallbacks_before

    # Automatic recovery: the channel re-engages and fallbacks stop.
    _engage(c, lambda: c.inc.remote())
    steady = runtime._direct_fallbacks
    cur = ray_tpu.get(c.inc.remote())
    assert ray_tpu.get([c.inc.remote() for _ in range(50)], timeout=30) \
        == list(range(cur + 1, cur + 51))
    assert runtime._direct_fallbacks == steady  # zero steady-state fallbacks


def test_backpressure_cap_and_death_through_pending_table(rt, monkeypatch):
    """ISSUE 12: the pending/replay table enforces the unanswered-call
    cap (a pipelined stream far deeper than the cap completes — the
    submitter parks on the table's condvar, the reader's completion
    pops release it) and a channel killed while calls are parked
    replays them exactly-once in order. Runs on whichever table the
    build provides (native or PyPendingTable) — the semantics must be
    identical."""
    from ray_tpu.core import runtime as rt_mod

    monkeypatch.setattr(rt_mod, "DIRECT_MAX_UNANSWERED", 8)

    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def inc(self):
            self.n += 1
            return self.n

    c = Counter.remote()
    st = _engage(c, lambda: c.inc.remote())
    chan = st["chan"]
    base = ray_tpu.get(c.inc.remote(), timeout=30)
    # 64-deep pipeline against a cap of 8: submit() must park and
    # resume repeatedly; the table can never exceed the cap.
    refs = [c.inc.remote() for _ in range(64)]
    assert len(chan.table) <= 8
    vals = ray_tpu.get(refs, timeout=60)
    assert vals == list(range(base + 1, base + 65))
    assert len(chan.table) == 0
    stats = _runtime().direct_stats()
    assert stats["gil_probe"]["py_entries"] > 0
    # Now kill the socket with calls in flight: drain() snapshots in
    # seq order, the NM replay keeps them exactly-once.
    refs = [c.inc.remote() for _ in range(20)]
    chan.conn.close()
    refs += [c.inc.remote() for _ in range(5)]
    vals = ray_tpu.get(refs, timeout=60)
    assert vals == list(range(base + 65, base + 90))
    st2 = _engage(c, lambda: c.inc.remote())
    assert st2["chan"] is not chan


def test_failure_sweeps_calls_popped_but_undelivered(rt):
    """A native burst can pop completions from the pending table and
    then die before Python ever sees them. The failure path must
    replay from the rich-state dict (_calls), not the table alone —
    otherwise those calls are never resolved and never replayed."""

    @ray_tpu.remote
    class Slow:
        def __init__(self):
            self.n = 0

        def inc(self):
            time.sleep(0.05)
            self.n += 1
            return self.n

    s = Slow.remote()
    st = _engage(s, lambda: s.inc.remote())
    chan = st["chan"]
    base = ray_tpu.get(s.inc.remote(), timeout=30)
    refs = [s.inc.remote() for _ in range(10)]
    # Simulate the undelivered-burst window: drop some in-flight task
    # ids from the table (as a dying recv_burst would), then sever the
    # channel. The sweep in _direct_channel_failed must still replay
    # every call exactly-once in order.
    for call in list(chan._calls.values())[:3]:
        chan.table.pop(call.spec.task_id.binary())
    chan.conn.close()
    vals = ray_tpu.get(refs, timeout=60)
    assert vals == list(range(base + 1, base + 11))


def test_actor_restart_reresolves_endpoint(rt):
    """Worker death with restarts left: calls fall back to the NM route
    (which queues through the restart), and the handle re-resolves the
    NEW worker's direct endpoint afterwards."""

    @ray_tpu.remote(max_restarts=1)
    class Flaky:
        def __init__(self):
            self.calls = 0

        def bump(self):
            self.calls += 1
            return self.calls

        def die(self):
            import os

            os._exit(1)

    f = Flaky.remote()
    st = _engage(f, lambda: f.bump.remote())
    old_chan = st["chan"]
    f.die.remote()
    # Post-restart state is fresh (__init__ re-ran); calls must succeed
    # again without manual re-resolution.
    deadline = time.time() + 30
    val = None
    while time.time() < deadline:
        try:
            val = ray_tpu.get(f.bump.remote(), timeout=10)
            break
        except Exception:
            time.sleep(0.2)
    assert val is not None and val >= 1
    st = _engage(f, lambda: f.bump.remote(), deadline_s=20)
    assert st["chan"] is not old_chan  # new endpoint, new channel


def test_worker_caller_rides_direct_plane(rt):
    """A task running INSIDE a worker calls an actor handle: the worker
    runtime opens its own direct channel (the serve-replica pattern),
    results flow, and the actor's NM sees the completion notifications."""

    @ray_tpu.remote
    class Adder:
        def add(self, a, b):
            return a + b

    @ray_tpu.remote
    def burst(handle, n):
        # Sequential gets so the worker runtime's discovery (spawned on
        # the first NM-routed call) gets a drain window to flip the
        # channel ready mid-burst; the worker process — and therefore
        # its runtime and channel — persists across burst() calls.
        return [ray_tpu.get(handle.add.remote(i, 1)) for i in range(n)]

    a = Adder.remote()
    _engage(a, lambda: a.add.remote(0, 0))
    # Drive worker-caller bursts until the NM has seen direct
    # completion notifications (worker channels engage across bursts).
    nm = _runtime()._nm
    deadline = time.time() + 45
    while time.time() < deadline:
        out = ray_tpu.get(burst.remote(a, 25), timeout=60)
        assert out == [i + 1 for i in range(25)]
        if nm._stats["direct_calls_done"] > 0:
            break
    assert nm._stats["direct_calls_done"] > 0
    assert nm._stats["direct_done_batches"] > 0


def test_serve_handle_rides_direct_plane(rt):
    """Serve replicas are actor handles: after a few requests the
    router's replica calls run over a ready direct channel and the
    request path answers correctly."""
    from ray_tpu import serve

    @serve.deployment
    class Doubler:
        def __call__(self, x):
            return x * 2

    handle = serve.run(Doubler.bind())
    try:
        assert handle.remote(21).result(timeout=30) == 42
        for i in range(30):
            assert handle.remote(i).result(timeout=30) == 2 * i
        # The handle's submits happen in the driver process here; its
        # runtime must hold a ready channel to the replica actor.
        states = _runtime()._direct_states
        deadline = time.time() + 15
        ready = False
        while time.time() < deadline and not ready:
            handle.remote(1).result(timeout=30)
            ready = any(
                s["status"] == "ready" for s in list(states.values())
            )
            time.sleep(0.05)
        assert ready, {
            k.hex()[:8]: s["status"] for k, s in states.items()
        }
    finally:
        serve.shutdown()


def test_out_of_order_frames_execute_in_sequence(rt):
    """Protocol-level: frames arriving with shuffled sequence numbers
    execute in sequence order (the worker parks the gap until it
    fills). Speaks the direct protocol over a raw connection."""
    from ray_tpu.core.ids import TaskID
    from ray_tpu.core.protocol import DIRECT_PROTO_VER, connect_unix
    from ray_tpu.core.task_spec import TaskSpec, TaskType

    @ray_tpu.remote
    class Rec:
        def __init__(self):
            self.seen = []

        def note(self, tag):
            self.seen.append(tag)
            return list(self.seen)

        def seen_list(self):
            return list(self.seen)

    r = Rec.remote()
    ray_tpu.get(r.seen_list.remote())
    runtime = _runtime()
    desc = runtime._nm.call_sync(
        runtime._nm.get_actor_direct(r.actor_id, timeout=15.0),
        timeout=30.0,
    )
    assert desc is not None and desc["path"]
    conn = connect_unix(desc["path"], timeout=5.0)
    try:
        conn.send({
            "type": "direct_hello", "ver": DIRECT_PROTO_VER, "token": "",
            "actor_id": r.actor_id.hex(), "node": runtime.node_id.hex(),
        })
        welcome = conn.recv()
        assert welcome.get("ok"), welcome

        def spec_for(tag):
            return TaskSpec(
                task_id=TaskID.from_random(),
                task_type=TaskType.ACTOR_TASK,
                function_id=r._class_function_id,
                args=[], kwargs={},
                num_returns=1,
                name="Rec.note",
                actor_id=r.actor_id,
                method_name="note",
            )

        from ray_tpu.core.task_spec import ValueArg
        from ray_tpu.core.serialization import serialize

        def arg(v):
            return ValueArg(serialize(v).to_bytes())

        s1, s2, s3 = spec_for("a"), spec_for("b"), spec_for("c")
        s1.args, s2.args, s3.args = [arg("a")], [arg("b")], [arg("c")]
        # Send seq 2 and 3 FIRST, then seq 1: the worker must buffer
        # them and execute a, b, c in sequence order.
        conn.send({"type": "execute", "spec": s2, "function_blob": None,
                   "q": 2})
        conn.send({"type": "execute", "spec": s3, "function_blob": None,
                   "q": 3})
        time.sleep(0.3)  # give the gap a chance to (wrongly) execute
        conn.send({"type": "execute", "spec": s1, "function_blob": None,
                   "q": 1})
        got = []
        deadline = time.time() + 20
        while len(got) < 3 and time.time() < deadline:
            msg = conn.recv()
            if msg.get("type") == "task_done":
                got.append(msg)
            elif msg.get("type") == "task_done_batch":
                got.extend(msg["items"])
        assert len(got) == 3
    finally:
        conn.close()
    assert ray_tpu.get(r.seen_list.remote(), timeout=15) == ["a", "b", "c"]


def test_version_mismatch_falls_back_to_nm_path(rt):
    """A hello with the wrong protocol version is refused; calls keep
    flowing over the NM route (transparent fallback, correct results)."""
    from ray_tpu.core.protocol import connect_unix

    @ray_tpu.remote
    class P:
        def ping(self):
            return b"ok"

    p = P.remote()
    st = _engage(p, lambda: p.ping.remote())
    desc = dict(st["chan"].desc)
    conn = connect_unix(desc["path"], timeout=5.0)
    try:
        conn.send({
            "type": "direct_hello", "ver": 999999, "token": "",
            "actor_id": p.actor_id.hex(), "node": "feedface",
        })
        welcome = conn.recv()
        assert not welcome.get("ok")
        assert "version" in welcome.get("error", "")
    finally:
        conn.close()
    # The real channel is untouched; calls still work.
    assert ray_tpu.get(p.ping.remote(), timeout=15) == b"ok"


def test_peer_close_fails_pending_requests_immediately():
    """PeerClient.close() must fail in-flight request() futures NOW —
    not after the 60s default timeout — including when close() is
    driven from a foreign thread (node-death handling)."""
    import asyncio

    from ray_tpu.core.peers import PeerClient
    from ray_tpu.core.protocol import AioFramedWriter, aio_read_frame

    async def scenario():
        async def silent_server(reader, writer):
            # Accept the hello, then never reply to anything.
            try:
                framed = AioFramedWriter(writer)
                while True:
                    await aio_read_frame(reader)
            except Exception:
                pass
            finally:
                del framed

        server = await asyncio.start_server(
            silent_server, "127.0.0.1", 0
        )
        port = server.sockets[0].getsockname()[1]
        peer = PeerClient("deadbeef" * 4, "127.0.0.1", port,
                          "cafebabe" * 4)
        await peer.connect()

        async def do_request():
            t0 = time.monotonic()
            with pytest.raises(ConnectionError):
                # Default timeout is 60s; close() must beat it by far.
                await peer.request({"type": "state_snapshot"})
            return time.monotonic() - t0

        task = asyncio.ensure_future(do_request())
        await asyncio.sleep(0.2)  # request is in flight, unanswered
        loop = asyncio.get_running_loop()
        # Foreign-thread close, like the NM's node-death teardown path.
        t = threading.Thread(target=peer.close)
        t.start()
        elapsed = await asyncio.wait_for(task, timeout=10)
        t.join(timeout=5)
        server.close()
        await server.wait_closed()
        return elapsed

    elapsed = asyncio.new_event_loop().run_until_complete(scenario())
    assert elapsed < 5.0, (
        f"pending request survived {elapsed:.1f}s after close() — "
        "futures must fail immediately on peer death"
    )
