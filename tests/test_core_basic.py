"""Core task/object API tests (ref analogue: python/ray/tests/test_basic.py)."""

import numpy as np
import pytest

import ray_tpu


def test_put_get(ray_tpu_start):
    ref = ray_tpu.put(42)
    assert ray_tpu.get(ref) == 42


def test_put_get_large_numpy(ray_tpu_start):
    arr = np.arange(1_000_000, dtype=np.float32)
    ref = ray_tpu.put(arr)
    out = ray_tpu.get(ref)
    np.testing.assert_array_equal(arr, out)


def test_simple_task(ray_tpu_start):
    @ray_tpu.remote
    def add(a, b):
        return a + b

    assert ray_tpu.get(add.remote(1, 2)) == 3


def test_task_with_ref_arg(ray_tpu_start):
    @ray_tpu.remote
    def double(x):
        return 2 * x

    ref = ray_tpu.put(21)
    assert ray_tpu.get(double.remote(ref)) == 42


def test_task_chain(ray_tpu_start):
    @ray_tpu.remote
    def inc(x):
        return x + 1

    ref = inc.remote(0)
    for _ in range(9):
        ref = inc.remote(ref)
    assert ray_tpu.get(ref) == 10


def test_many_parallel_tasks(ray_tpu_start):
    @ray_tpu.remote
    def square(i):
        return i * i

    refs = [square.remote(i) for i in range(50)]
    assert ray_tpu.get(refs) == [i * i for i in range(50)]


def test_large_task_output(ray_tpu_start):
    @ray_tpu.remote
    def make_array(n):
        return np.ones(n, dtype=np.float64)

    out = ray_tpu.get(make_array.remote(500_000))
    assert out.shape == (500_000,)
    assert out.sum() == 500_000


def test_large_task_arg(ray_tpu_start):
    arr = np.random.rand(300_000)

    @ray_tpu.remote
    def total(x):
        return float(x.sum())

    assert abs(ray_tpu.get(total.remote(arr)) - arr.sum()) < 1e-6


def test_multiple_returns(ray_tpu_start):
    @ray_tpu.remote(num_returns=3)
    def three():
        return 1, 2, 3

    a, b, c = three.remote()
    assert ray_tpu.get([a, b, c]) == [1, 2, 3]


def test_task_error_propagates(ray_tpu_start):
    @ray_tpu.remote
    def boom():
        raise ValueError("kaboom")

    with pytest.raises(ValueError, match="kaboom"):
        ray_tpu.get(boom.remote())


def test_error_propagates_through_lineage(ray_tpu_start):
    @ray_tpu.remote
    def boom():
        raise ValueError("kaboom")

    @ray_tpu.remote
    def consume(x):
        return x

    with pytest.raises(Exception):
        ray_tpu.get(consume.remote(boom.remote()))


def test_wait(ray_tpu_start):
    import time

    @ray_tpu.remote
    def fast():
        return "fast"

    @ray_tpu.remote
    def slow():
        time.sleep(5)
        return "slow"

    f, s = fast.remote(), slow.remote()
    ready, not_ready = ray_tpu.wait([f, s], num_returns=1, timeout=3)
    assert ready == [f]
    assert not_ready == [s]


def test_wait_timeout_empty(ray_tpu_start):
    import time

    @ray_tpu.remote
    def slow():
        time.sleep(5)

    ready, not_ready = ray_tpu.wait([slow.remote()], num_returns=1, timeout=0.2)
    assert ready == []
    assert len(not_ready) == 1


def test_get_timeout(ray_tpu_start):
    import time

    @ray_tpu.remote
    def slow():
        time.sleep(10)

    with pytest.raises(ray_tpu.GetTimeoutError):
        ray_tpu.get(slow.remote(), timeout=0.2)


def test_nested_tasks(ray_tpu_start):
    @ray_tpu.remote
    def inner(x):
        return x * 2

    @ray_tpu.remote
    def outer(x):
        return ray_tpu.get(inner.remote(x)) + 1

    assert ray_tpu.get(outer.remote(10)) == 21


def test_nested_ref_passthrough(ray_tpu_start):
    @ray_tpu.remote
    def make():
        return 7

    @ray_tpu.remote
    def passthrough(refs):
        # Nested (non-top-level) refs are not resolved automatically.
        return ray_tpu.get(refs[0])

    assert ray_tpu.get(passthrough.remote([make.remote()])) == 7


def test_cluster_resources(ray_tpu_start):
    res = ray_tpu.cluster_resources()
    assert res["CPU"] == 4


def test_kwargs(ray_tpu_start):
    @ray_tpu.remote
    def f(a, b=10):
        return a + b

    assert ray_tpu.get(f.remote(1)) == 11
    assert ray_tpu.get(f.remote(1, b=2)) == 3


def test_options_name(ray_tpu_start):
    @ray_tpu.remote
    def f():
        return 1

    assert ray_tpu.get(f.options(name="custom").remote()) == 1
