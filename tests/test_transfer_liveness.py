"""Control-plane liveness while the transfer data plane streams a large
object (own module: the shared test_transfer cluster must be torn down
before this test builds one with a raised node-death timeout)."""

import asyncio
import threading
import time

import numpy as np

import ray_tpu
from ray_tpu.cluster_utils import Cluster

CHUNK = 256 * 1024


def _nm():
    from ray_tpu.core.runtime_context import current_runtime

    return current_runtime()._nm


def test_control_plane_live_during_large_pull():
    """Peer-channel RPCs stay fast while a large object streams: the
    data plane keeps payload OFF the control socket, so state_snapshot
    round trips must not queue behind gigabytes (acceptance: p99 under
    50 ms; the old protocol serialized 5 MiB pickle frames ahead of
    every RPC). Own cluster: failure detection is not under test, so
    the death timeout is raised — CPU-starved heartbeats on a saturated
    CI box must not fail the latency measurement with a dead node. The
    measurement itself retries once: p99 over ~100 samples on a shared
    2-core CI host carries scheduler noise that is not a product
    regression."""
    c = Cluster(
        head_resources={"CPU": 2},
        system_config={
            "num_prestart_workers": 1,
            "default_max_retries": 0,
            "object_transfer_chunk_bytes": CHUNK,
            "node_death_timeout_s": 15.0,
            "log_to_driver": False,
        },
    )
    try:
        _control_plane_liveness_body(c)
    finally:
        c.shutdown()


def _measure_pull_with_rpcs(nm, produce, nbytes, peer_hex):
    """One measured pull: stream ``nbytes`` from the peer while hammering
    its control channel with state_snapshot RPCs; returns sorted
    latencies (ms)."""
    ref = produce.remote()
    ray_tpu.wait([ref], timeout=120)

    latencies = []
    stop = threading.Event()

    async def one_rpc():
        peer = await nm._get_peer(peer_hex)
        t0 = time.perf_counter()
        await peer.request({"type": "state_snapshot"}, timeout=30)
        return (time.perf_counter() - t0) * 1e3

    def rpc_loop():
        while not stop.is_set() and len(latencies) < 200:
            fut = asyncio.run_coroutine_threadsafe(one_rpc(), nm._loop)
            latencies.append(fut.result(timeout=30))

    t = threading.Thread(target=rpc_loop)
    t.start()
    got = ray_tpu.get(ref, timeout=300)
    stop.set()
    t.join(timeout=60)
    assert got.nbytes == nbytes
    del got, ref
    latencies.sort()
    return latencies


def _control_plane_liveness_body(cluster):
    cluster.add_node(num_cpus=2, resources={"gadget": 2})
    nm = _nm()
    nbytes = 128 * 1024 * 1024

    @ray_tpu.remote(resources={"gadget": 1})
    def produce():
        return np.ones(nbytes // 8, dtype=np.int64)

    ray_tpu.get(produce.remote(), timeout=180)  # warm
    peer_hex = next(h for h in nm._cluster_view
                    if h != nm.node_id.hex())

    p99 = None
    for attempt in range(2):
        latencies = _measure_pull_with_rpcs(nm, produce, nbytes, peer_hex)
        assert len(latencies) >= 20, "not enough concurrent RPC samples"
        p99 = latencies[min(len(latencies) - 1,
                            int(len(latencies) * 0.99))]
        if p99 < 50.0:
            break
    assert p99 is not None and p99 < 50.0, (
        f"peer-channel RPC p99 {p99:.1f} ms during a {nbytes >> 20} MiB "
        f"pull (both attempts)"
    )
    st = nm._transfer.stats
    assert st["striped_pulls"] >= 1, st
