"""RLlib family tests: DDPG, A2C, MARWIL, bandits, ES, ARS.

Each family trains on a seconds-scale toy task that its reference
analogue (rllib/algorithms/<name>) demonstrably solves; envs live
inside factories so cloudpickle ships them by value (this test module
is not importable from worker processes).
"""

import sys as _sys

import cloudpickle as _cloudpickle
import numpy as np
import pytest

# Env factories below are module-level; workers cannot import this test
# module, so ship everything from it by value.
_cloudpickle.register_pickle_by_value(_sys.modules[__name__])


def _go_to_zero_env():
    """1-D continuous toy: reward -|x + a|; optimum a = -x."""
    import numpy as _np

    class _Box:
        def __init__(self, low, high, shape):
            self.low = _np.full(shape, low, dtype=_np.float32)
            self.high = _np.full(shape, high, dtype=_np.float32)
            self.shape = shape

    class GoToZero:
        def __init__(self):
            self.observation_space = _Box(-1.0, 1.0, (1,))
            self.action_space = _Box(-1.0, 1.0, (1,))
            self._rng = _np.random.RandomState(0)
            self._t = 0

        def reset(self, seed=None):
            if seed is not None:
                self._rng = _np.random.RandomState(seed)
            self._t = 0
            self._x = self._rng.uniform(-1, 1, (1,)).astype("float32")
            return self._x, {}

        def step(self, action):
            r = -float(abs(self._x[0] + float(action[0])))
            self._t += 1
            self._x = self._rng.uniform(-1, 1, (1,)).astype("float32")
            return self._x, r, False, self._t >= 50, {}

    return GoToZero()


def _sign_env():
    """Discrete toy: obs=[signal in {-1,+1}]; action must match the
    sign (+1 reward, else -1); 30-step episodes."""
    import numpy as _np

    class _Box:
        def __init__(self, shape):
            self.shape = shape

    class _Disc:
        n = 2
        shape = ()

    class Sign:
        def __init__(self):
            self.observation_space = _Box((1,))
            self.action_space = _Disc()
            self._rng = _np.random.RandomState(0)
            self._t = 0

        def _obs(self):
            self._sig = float(self._rng.choice([-1.0, 1.0]))
            return _np.asarray([self._sig], "float32")

        def reset(self, seed=None):
            if seed is not None:
                self._rng = _np.random.RandomState(seed)
            self._t = 0
            return self._obs(), {}

        def step(self, action):
            want = 1 if self._sig > 0 else 0
            r = 1.0 if int(action) == want else -1.0
            self._t += 1
            return self._obs(), r, False, self._t >= 30, {}

    return Sign()


@pytest.mark.slow
def test_ddpg_learns_continuous_control(ray_tpu_start):
    """DDPG (single critic, undelayed actor) reaches the a=-x optimum
    (ref: rllib/algorithms/ddpg)."""
    from ray_tpu.rllib import DDPGConfig

    config = (
        DDPGConfig()
        .environment(_go_to_zero_env)
        .env_runners(num_env_runners=2, rollout_fragment_length=100)
        .training(lr=3e-3, minibatch_size=128,
                  num_updates_per_iteration=60,
                  num_steps_sampled_before_learning_starts=200,
                  exploration_noise=0.2)
    )
    algo = config.build()
    try:
        first = algo.train()
        last = {}
        for _ in range(15):
            last = algo.train()
        assert last["num_learner_updates"] > 0
        assert np.isfinite(last["critic_loss"])
        assert "actor_loss" in last
        assert last["episode_reward_mean"] > \
            first["episode_reward_mean"] + 4, (first, last)
        assert last["episode_reward_mean"] > -12, last
    finally:
        algo.stop()


@pytest.mark.slow
def test_a2c_learns_sign_task(ray_tpu_start):
    """A2C (single-epoch policy gradient) solves sign matching (ref:
    rllib/algorithms/a2c)."""
    from ray_tpu.rllib import A2CConfig

    config = (
        A2CConfig()
        .environment(_sign_env)
        .env_runners(num_env_runners=2, rollout_fragment_length=120)
        .training(lr=5e-3, train_batch_size=240, minibatch_size=240)
        .debugging(seed=0)
    )
    algo = config.build()
    try:
        best = -31.0
        for _ in range(20):
            result = algo.train()
            if result["episodes_total"] > 0:
                best = max(best, result["episode_reward_mean"])
            if best > 24:
                break
        # Random play ~0; optimal 30.
        assert best > 24, best
    finally:
        algo.stop()


@pytest.mark.slow
def test_marwil_prefers_high_return_actions(ray_tpu_start):
    """MARWIL up-weights better-than-average logged actions: when only
    30% of the logged rows take the (high-return) expert action, BC
    (beta=0) imitates the 70% majority's mistake while beta>0 recovers
    the expert (ref: rllib/algorithms/marwil)."""
    import ray_tpu.data as rd
    from ray_tpu.rllib import MARWILConfig

    rng = np.random.RandomState(0)
    n = 2048
    obs = rng.randn(n, 4).astype("float32")
    expert = (obs[:, 0] + obs[:, 1] > 0).astype("int64")
    # 70% of rows log the WRONG action (with its low return).
    action = np.where(rng.rand(n) < 0.3, expert, 1 - expert)
    ret = np.where(action == expert, 1.0, -1.0).astype("float32")
    ds = rd.from_items(
        [{"obs": obs[i], "action": int(action[i]),
          "return": float(ret[i])} for i in range(n)],
        override_num_blocks=4,
    )

    def accuracy(algo):
        policy = algo.get_policy()
        test_obs = rng.randn(512, 4).astype("float32")
        want = (test_obs[:, 0] + test_obs[:, 1] > 0).astype("int64")
        logits, _ = policy.logits_and_value(test_obs)
        return float((logits.argmax(axis=1) == want).mean())

    cfg = MARWILConfig().offline_data(ds).training(
        lr=5e-3, minibatch_size=256, beta=2.0
    )
    cfg.num_actions = 2
    algo = cfg.build()
    for _ in range(30):
        last = algo.train()
    assert last["num_rows_trained"] == n
    acc = accuracy(algo)
    assert acc > 0.85, acc

    # beta=0 is BC: cross-entropy's argmax imitates the 70% majority,
    # i.e. the WRONG action.
    cfg0 = MARWILConfig().offline_data(ds).training(
        lr=5e-3, minibatch_size=256, beta=0.0
    )
    cfg0.num_actions = 2
    bc_like = cfg0.build()
    for _ in range(30):
        bc_like.train()
    bc_acc = accuracy(bc_like)
    assert bc_acc < 0.5, bc_acc
    assert acc > bc_acc + 0.3, (acc, bc_acc)


def _bandit_env():
    """Contextual bandit: x ~ unit ball in R^2, 3 arms with fixed
    weight vectors; reward = theta_a . x (+ noise); 1-step episodes."""
    import numpy as _np

    class _Box:
        def __init__(self, shape):
            self.shape = shape

    class _Disc:
        n = 3
        shape = ()

    class LinBandit:
        THETA = _np.asarray([[1.0, 0.0], [0.0, 1.0], [-0.7, -0.7]])

        def __init__(self):
            self.observation_space = _Box((2,))
            self.action_space = _Disc()
            self._rng = _np.random.RandomState(0)

        def _ctx(self):
            x = self._rng.randn(2)
            self._x = (x / _np.linalg.norm(x)).astype("float32")
            return self._x

        def reset(self, seed=None):
            if seed is not None:
                self._rng = _np.random.RandomState(seed)
            return self._ctx(), {}

        def step(self, action):
            r = float(self.THETA[int(action)] @ self._x)
            r += 0.05 * float(self._rng.randn())
            return self._ctx(), r, True, False, {}

    return LinBandit()


@pytest.mark.parametrize("mode", ["ucb", "ts"])
def test_bandit_linear(ray_tpu_start, mode):
    """LinUCB/LinTS approach the oracle arm's mean reward (ref:
    rllib/algorithms/bandit)."""
    from ray_tpu.rllib import BanditLinTSConfig, BanditLinUCBConfig

    cls = BanditLinUCBConfig if mode == "ucb" else BanditLinTSConfig
    config = (
        cls()
        .environment(_bandit_env)
        .env_runners(num_env_runners=2, rollout_fragment_length=64)
    )
    algo = config.build()
    try:
        for _ in range(10):
            result = algo.train()
        # Oracle mean = E[max_a theta_a . x] ~ 0.85 on the unit circle;
        # uniform play ~ 0.04. The cumulative mean lags the converged
        # policy, so the bar is modest but far above random.
        assert result["mean_reward"] > 0.5, result
        w = algo.get_weights()
        assert w["theta"].shape == (3, 2)
    finally:
        algo.stop()


@pytest.mark.slow
def test_es_learns_sign_task(ray_tpu_start):
    """ES improves the deterministic policy purely by parameter-space
    search (ref: rllib/algorithms/es)."""
    from ray_tpu.rllib import ESConfig

    config = (
        ESConfig()
        .environment(_sign_env)
        .env_runners(num_env_runners=2)
        .debugging(seed=0)
    )
    config.episodes_per_batch = 12
    config.sigma = 0.2
    config.step_size = 0.2
    config.episode_horizon = 30
    algo = config.build()
    try:
        best = -31.0
        for _ in range(25):
            result = algo.train()
            best = max(best, result["episode_reward_mean"])
            if best > 24:
                break
        assert best > 24, best
        assert result["episodes_total"] > 0
    finally:
        algo.stop()


@pytest.mark.slow
def test_ars_learns_sign_task(ray_tpu_start):
    """ARS (top-k directions, std-normalized step) matches ES on the
    toy task (ref: rllib/algorithms/ars)."""
    from ray_tpu.rllib import ARSConfig

    config = (
        ARSConfig()
        .environment(_sign_env)
        .env_runners(num_env_runners=2)
        .debugging(seed=0)
    )
    config.episodes_per_batch = 12
    config.top_directions = 6
    config.sigma = 0.2
    config.step_size = 0.2
    config.episode_horizon = 30
    algo = config.build()
    try:
        best = -31.0
        for _ in range(25):
            result = algo.train()
            best = max(best, result["episode_reward_mean"])
            if best > 24:
                break
        assert best > 24, best
    finally:
        algo.stop()


def test_flatten_roundtrip():
    """ES flat-vector codec: unflatten(flatten(t)) == t."""
    from ray_tpu.rllib.es import flatten_params, unflatten_params

    rng = np.random.RandomState(0)
    tree = {
        "trunk": [(rng.randn(3, 4).astype("float32"),
                   rng.randn(4).astype("float32")),
                  (rng.randn(4, 2).astype("float32"),
                   rng.randn(2).astype("float32"))],
        "pi": [(rng.randn(2, 5).astype("float32"),
                rng.randn(5).astype("float32"))],
    }
    vec, spec = flatten_params(tree)
    back = unflatten_params(vec, spec)
    for name in tree:
        for (W, b), (W2, b2) in zip(tree[name], back[name]):
            np.testing.assert_allclose(W, W2, rtol=1e-6)
            np.testing.assert_allclose(b, b2, rtol=1e-6)
