"""Striped zero-copy transfer data plane (core/data_channel.py +
core/object_transfer.py): parity with the control-plane chunk protocol,
stripe reassembly, fallback + recovery when a peer's data server dies,
admission control, per-node pull dedup, and control-plane liveness under
a large concurrent pull (the round-5 regression this plane fixes: every
chunk rode the pickled peer socket at 0.25 GB/s)."""

import asyncio
import hashlib
import threading
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster

CHUNK = 256 * 1024  # head-side chunk size; forces multi-stripe pulls


@pytest.fixture(scope="module")
def cluster():
    """One cluster for the read-only-plane tests (parity, reassembly,
    dedup assert on stat DELTAS, so sharing is safe and saves ~10s of
    suite wall clock); the death/recovery and liveness tests build their
    own."""
    c = Cluster(
        head_resources={"CPU": 2},
        system_config={
            "num_prestart_workers": 1,
            "default_max_retries": 0,
            "object_transfer_chunk_bytes": CHUNK,
            "log_to_driver": False,
        },
    )
    c.add_node(num_cpus=1, resources={"gadget": 1})
    yield c
    c.shutdown()


def _nm():
    from ray_tpu.core.runtime_context import current_runtime

    return current_runtime()._nm


def test_small_large_parity_through_data_plane(cluster):
    """Small objects still answer inline in one control round trip;
    large ones stream over the data plane — both byte-exact."""

    @ray_tpu.remote(resources={"gadget": 1})
    def small():
        return b"tiny-payload"

    @ray_tpu.remote(resources={"gadget": 1})
    def large():
        rng = np.random.RandomState(7)
        return rng.randint(0, 255, size=CHUNK * 13 + 12345, dtype=np.uint8)

    st = _nm()._transfer.stats
    chunked_before = st["chunked_pulls"]
    striped_before = st["striped_pulls"]
    bytes_before = st["bytes_pulled_stream"]
    assert ray_tpu.get(small.remote(), timeout=60) == b"tiny-payload"
    assert st["chunked_pulls"] == chunked_before  # inline path untouched

    got = ray_tpu.get(large.remote(), timeout=120)
    rng = np.random.RandomState(7)
    expected = rng.randint(0, 255, size=CHUNK * 13 + 12345, dtype=np.uint8)
    assert np.array_equal(got, expected)
    assert st["striped_pulls"] > striped_before, st
    assert st["bytes_pulled_stream"] >= bytes_before + CHUNK * 13, st
    assert st["fallback_pulls"] == 0, st


def test_stripe_reassembly_64mib_checksum(cluster):
    """A 64 MiB object striped across the stream pool reassembles
    byte-exactly (checksummed at the source, re-checksummed after the
    pull lands in the local store)."""
    nbytes = 64 * 1024 * 1024

    @ray_tpu.remote(resources={"gadget": 1})
    def produce():
        arr = np.arange(nbytes // 8, dtype=np.int64)
        arr[::1009] = -arr[::1009]  # break monotonic patterns
        return hashlib.sha256(arr.tobytes()).hexdigest(), arr

    st = _nm()._transfer.stats
    striped_before = st["striped_pulls"]
    digest, arr = ray_tpu.get(produce.remote(), timeout=180)
    assert hashlib.sha256(arr.tobytes()).hexdigest() == digest
    assert st["striped_pulls"] > striped_before, st
    assert st["fallback_pulls"] == 0, st


def test_data_plane_death_falls_back_then_recovers(cluster):
    """Kill the serving node's data server mid-life: pulls fall back to
    the control-plane chunk protocol (correct, just slower); restart it
    and the next pull streams again — the port is re-learned from every
    locate reply, so recovery needs no cluster-wide coordination."""
    nm = _nm()
    # Remote nodes run default config (5 MiB chunks): objects must beat
    # their inline threshold for the chunked path to engage.
    nbytes = 8 * 1024 * 1024

    @ray_tpu.remote(resources={"gadget": 1})
    def consume(a):
        return int(a.sum())

    def roundtrip():
        arr = np.ones(nbytes // 8, dtype=np.int64)
        ref = ray_tpu.put(arr)
        assert ray_tpu.get(consume.remote(ref), timeout=120) == arr.size

    st = nm._transfer.stats
    roundtrip()
    assert st["ranges_served"] >= 1, st  # served over the data plane

    # Data server dies (peer keeps running).
    nm._data_server.stop()
    nm.data_port = 0
    chunks_before = st["chunks_served"]
    roundtrip()
    assert st["chunks_served"] > chunks_before, st  # fell back, worked

    # Recovery: restart, next pull streams again.
    nm.data_port = nm._data_server.start()
    ranges_before = st["ranges_served"]
    roundtrip()
    assert st["ranges_served"] > ranges_before, st


def test_admission_timeout_raises_transfer_error():
    """Admission control survives the rewrite: an impossible pull fails
    immediately, a merely-starved one fails after the admission timeout
    — both as TransferError, never a crashed shm allocation."""
    from ray_tpu.core.config import Config
    from ray_tpu.core.object_store import ObjectDirectory
    from ray_tpu.core.object_transfer import ObjectTransfer, TransferError

    class FakeNM:
        def __init__(self, loop):
            self.config = Config()
            self.config.pull_admission_timeout_s = 0.2
            self.directory = ObjectDirectory(capacity_bytes=1024)
            self._loop = loop
            self.spilled = []

        class _Id:
            @staticmethod
            def hex():
                return "00" * 16

        node_id = _Id()

        def _maybe_spill(self, need=0):
            self.spilled.append(need)

    async def scenario():
        nm = FakeNM(asyncio.get_event_loop())
        transfer = ObjectTransfer(nm)
        try:
            # Bigger than the whole store: immediate, no timeout wait.
            with pytest.raises(TransferError, match="exceeds the object"):
                await transfer._admit_bytes(4096)
            # Fits the store but the store is full: queue, then time out
            # (the spill pass was asked but freed nothing).
            nm.directory.used_bytes = 1024
            t0 = time.monotonic()
            with pytest.raises(TransferError, match="not admitted"):
                await transfer._admit_bytes(512)
            assert time.monotonic() - t0 >= 0.2
            assert nm.spilled, "spill pass never consulted"
            assert transfer.stats["pulls_queued_on_memory"] == 1
        finally:
            transfer.close()

    asyncio.new_event_loop().run_until_complete(scenario())


def test_concurrent_gets_dedup_to_one_transfer(cluster):
    """N concurrent local requesters of one remote object share a single
    pull (node-manager _pulls future table): the wire sees one striped
    transfer, not N."""

    @ray_tpu.remote(resources={"gadget": 1})
    def produce():
        return np.ones(CHUNK * 24 // 8, dtype=np.int64)

    ref = produce.remote()
    ray_tpu.wait([ref], timeout=120)
    st = _nm()._transfer.stats
    chunked_before = st["chunked_pulls"]
    striped_before = st["striped_pulls"]

    results, errors = [], []

    def getter():
        try:
            results.append(ray_tpu.get(ref, timeout=120).size)
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=getter) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors, errors
    assert results == [CHUNK * 24 // 8] * 4
    assert st["chunked_pulls"] == chunked_before + 1, st  # ONE transfer
    assert st["striped_pulls"] == striped_before + 1, st
