"""JaxTrainer tests: session plumbing, checkpointing, failure restart, and
the PR1 e2e config (ResNet-18 on synthetic CIFAR, 1 CPU worker)."""

import os

import numpy as np
import pytest

import ray_tpu
from ray_tpu import train as rt_train
from ray_tpu.train import (
    Checkpoint,
    CheckpointConfig,
    FailureConfig,
    JaxTrainer,
    RunConfig,
    ScalingConfig,
)


def test_trainer_basic_report(ray_tpu_start, tmp_path):
    def loop(config):
        for step in range(3):
            rt_train.report({"step": step, "loss": 1.0 / (step + 1)})

    result = JaxTrainer(
        loop,
        train_loop_config={},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(storage_path=str(tmp_path / "run1")),
    ).fit()
    assert result.error is None
    assert result.metrics["step"] == 2
    assert len(result.metrics_history) == 3


def test_trainer_two_workers_ranks(ray_tpu_start, tmp_path):
    def loop():
        rank = rt_train.get_world_rank()
        world = rt_train.get_world_size()
        rt_train.report({"rank": rank, "world": world})

    result = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(storage_path=str(tmp_path / "run2")),
    ).fit()
    assert result.error is None
    assert result.metrics["rank"] == 0
    assert result.metrics["world"] == 2


def test_trainer_checkpoint_roundtrip(ray_tpu_start, tmp_path):
    def loop(config):
        import jax.numpy as jnp

        sess = rt_train.get_session()
        params = {"w": jnp.asarray([1.0, 2.0, 3.0]), "step": jnp.asarray(7)}
        ckpt = Checkpoint.from_pytree(params, sess.checkpoint_dir(0))
        rt_train.report({"step": 0, "loss": 0.5}, checkpoint=ckpt)

    result = JaxTrainer(
        loop,
        train_loop_config={},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(storage_path=str(tmp_path / "run3")),
    ).fit()
    assert result.error is None
    assert result.checkpoint is not None
    restored = result.checkpoint.as_pytree()
    np.testing.assert_allclose(np.asarray(restored["w"]), [1.0, 2.0, 3.0])


def test_trainer_failure_restart_from_checkpoint(ray_tpu_start, tmp_path):
    marker = str(tmp_path / "crashed_once")

    def loop(config):
        import jax.numpy as jnp

        sess = rt_train.get_session()
        start = sess.get_checkpoint()
        start_step = int(start.as_pytree()["step"]) + 1 if start else 0
        for step in range(start_step, 4):
            if step == 2 and not os.path.exists(config["marker"]):
                open(config["marker"], "w").close()
                os._exit(1)  # hard crash mid-training
            ckpt = Checkpoint.from_pytree(
                {"step": jnp.asarray(step)}, sess.checkpoint_dir(step)
            )
            rt_train.report({"step": step}, checkpoint=ckpt)

    result = JaxTrainer(
        loop,
        train_loop_config={"marker": marker},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(
            storage_path=str(tmp_path / "run4"),
            failure_config=FailureConfig(max_failures=1),
        ),
    ).fit()
    assert result.error is None, result.error
    assert result.metrics["step"] == 3
    assert os.path.exists(marker)


def test_trainer_error_surfaces(ray_tpu_start, tmp_path):
    def loop():
        raise ValueError("train loop exploded")

    result = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(storage_path=str(tmp_path / "run5")),
    ).fit()
    assert result.error is not None
    assert "exploded" in str(result.error)


@pytest.mark.slow
def test_resnet_cifar_e2e(ray_tpu_start, tmp_path):
    """The PR1 reference config: ResNet-18, synthetic CIFAR-10, 1 CPU worker
    (BASELINE.json configs[0]) — loss must decrease."""

    def loop(config):
        import jax
        import jax.numpy as jnp
        import optax

        from ray_tpu.models import resnet18
        from ray_tpu.train.checkpoint import Checkpoint as Ckpt

        model = resnet18(num_classes=10, dtype=jnp.float32)
        rng = jax.random.PRNGKey(0)
        x = jax.random.normal(rng, (32, 32, 32, 3))
        y = jax.random.randint(jax.random.PRNGKey(1), (32,), 0, 10)
        variables = model.init(rng, x, train=True)
        params, batch_stats = variables["params"], variables["batch_stats"]
        tx = optax.adam(1e-2)
        opt_state = tx.init(params)

        @jax.jit
        def step(params, batch_stats, opt_state):
            def loss_fn(p):
                logits, updates = model.apply(
                    {"params": p, "batch_stats": batch_stats},
                    x, train=True, mutable=["batch_stats"],
                )
                loss = optax.softmax_cross_entropy_with_integer_labels(
                    logits, y
                ).mean()
                return loss, updates["batch_stats"]

            (loss, new_bs), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(params)
            updates, opt_state = tx.update(grads, opt_state)
            return optax.apply_updates(params, updates), new_bs, opt_state, loss

        sess = rt_train.get_session()
        first = last = None
        for i in range(8):
            params, batch_stats, opt_state, loss = step(
                params, batch_stats, opt_state
            )
            loss = float(loss)
            first = first if first is not None else loss
            last = loss
            rt_train.report({"step": i, "loss": loss})
        ckpt = Ckpt.from_pytree({"params": params}, sess.checkpoint_dir(8))
        rt_train.report({"step": 8, "loss": last, "first_loss": first},
                        checkpoint=ckpt)

    result = JaxTrainer(
        loop,
        train_loop_config={},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(storage_path=str(tmp_path / "resnet")),
    ).fit()
    assert result.error is None, result.error
    assert result.metrics["loss"] < result.metrics["first_loss"]
    assert result.checkpoint is not None
