"""gRPC ingress (ref: serve's gRPC proxy; here a generic bytes-in/
bytes-out router, serve/grpc_ingress.py)."""

import json

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture
def serve_rt(ray_tpu_start):
    yield
    serve.stop_grpc_ingress()
    serve.shutdown()


def test_grpc_ingress_roundtrip(serve_rt):
    import grpc

    @serve.deployment(num_replicas=2)
    class Tokenizer:
        def __call__(self, payload: bytes) -> bytes:
            return payload.upper()

        def stats(self, payload: bytes):
            return {"len": len(payload)}  # non-bytes -> JSON over the wire

    serve.run(Tokenizer.bind(), name="tok")
    port = serve.start_grpc_ingress(0)
    channel = grpc.insecure_channel(f"127.0.0.1:{port}")

    call = channel.unary_unary("/tok/__call__")
    assert call(b"shout", timeout=60) == b"SHOUT"

    stats = channel.unary_unary("/tok/stats")
    assert json.loads(stats(b"abcd", timeout=60)) == {"len": 4}

    # unknown deployment -> NOT_FOUND
    missing = channel.unary_unary("/nosuch/__call__")
    with pytest.raises(grpc.RpcError) as ei:
        missing(b"x", timeout=30)
    assert ei.value.code() == grpc.StatusCode.NOT_FOUND
    channel.close()


def test_per_node_grpc_proxies(serve_rt):
    """One gRPC ingress per node with dynamic route discovery (mirror of
    the per-node HTTP ProxyActor)."""
    import grpc

    from ray_tpu.serve.grpc_ingress import start_per_node_grpc_proxies

    @serve.deployment
    def upper(payload: bytes) -> bytes:
        return payload.upper()

    serve.run(upper.bind(), name="up")
    proxies = start_per_node_grpc_proxies(port=0)
    try:
        assert len(proxies) >= 1
        for _, port in proxies.values():  # every node's ingress serves
            channel = grpc.insecure_channel(f"127.0.0.1:{port}")
            assert channel.unary_unary("/up/__call__")(
                b"abc", timeout=60) == b"ABC"
            channel.close()
    finally:
        for actor, _ in proxies.values():
            try:
                ray_tpu.get(actor.shutdown.remote(), timeout=10)
                ray_tpu.kill(actor)
            except Exception:
                pass
