"""gRPC ingress (ref: serve's gRPC proxy; here a generic bytes-in/
bytes-out router, serve/grpc_ingress.py)."""

import json

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture
def serve_rt(ray_tpu_start):
    yield
    serve.stop_grpc_ingress()
    serve.shutdown()


def test_grpc_ingress_roundtrip(serve_rt):
    import grpc

    @serve.deployment(num_replicas=2)
    class Tokenizer:
        def __call__(self, payload: bytes) -> bytes:
            return payload.upper()

        def stats(self, payload: bytes):
            return {"len": len(payload)}  # non-bytes -> JSON over the wire

    serve.run(Tokenizer.bind(), name="tok")
    port = serve.start_grpc_ingress(0)
    channel = grpc.insecure_channel(f"127.0.0.1:{port}")

    call = channel.unary_unary("/tok/__call__")
    assert call(b"shout", timeout=60) == b"SHOUT"

    stats = channel.unary_unary("/tok/stats")
    assert json.loads(stats(b"abcd", timeout=60)) == {"len": 4}

    # unknown deployment -> NOT_FOUND
    missing = channel.unary_unary("/nosuch/__call__")
    with pytest.raises(grpc.RpcError) as ei:
        missing(b"x", timeout=30)
    assert ei.value.code() == grpc.StatusCode.NOT_FOUND
    channel.close()
