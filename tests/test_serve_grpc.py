"""gRPC ingress (ref: serve's gRPC proxy; here a generic bytes-in/
bytes-out router, serve/grpc_ingress.py)."""

import json

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture
def serve_rt(ray_tpu_start):
    yield
    serve.stop_grpc_ingress()
    serve.shutdown()


def test_grpc_ingress_roundtrip(serve_rt):
    import grpc

    @serve.deployment(num_replicas=2)
    class Tokenizer:
        def __call__(self, payload: bytes) -> bytes:
            return payload.upper()

        def stats(self, payload: bytes):
            return {"len": len(payload)}  # non-bytes -> JSON over the wire

    serve.run(Tokenizer.bind(), name="tok")
    port = serve.start_grpc_ingress(0)
    channel = grpc.insecure_channel(f"127.0.0.1:{port}")

    call = channel.unary_unary("/tok/__call__")
    assert call(b"shout", timeout=60) == b"SHOUT"

    stats = channel.unary_unary("/tok/stats")
    assert json.loads(stats(b"abcd", timeout=60)) == {"len": 4}

    # unknown deployment -> NOT_FOUND
    missing = channel.unary_unary("/nosuch/__call__")
    with pytest.raises(grpc.RpcError) as ei:
        missing(b"x", timeout=30)
    assert ei.value.code() == grpc.StatusCode.NOT_FOUND
    channel.close()


def test_per_node_grpc_proxies(serve_rt):
    """One gRPC ingress per node with dynamic route discovery (mirror of
    the per-node HTTP ProxyActor)."""
    import grpc

    from ray_tpu.serve.grpc_ingress import start_per_node_grpc_proxies

    @serve.deployment
    def upper(payload: bytes) -> bytes:
        return payload.upper()

    serve.run(upper.bind(), name="up")
    proxies = start_per_node_grpc_proxies(port=0)
    try:
        assert len(proxies) >= 1
        for _, port in proxies.values():  # every node's ingress serves
            channel = grpc.insecure_channel(f"127.0.0.1:{port}")
            assert channel.unary_unary("/up/__call__")(
                b"abc", timeout=60) == b"ABC"
            channel.close()
    finally:
        for actor, _ in proxies.values():
            try:
                ray_tpu.get(actor.shutdown.remote(), timeout=10)
                ray_tpu.kill(actor)
            except Exception:
                pass


def test_grpc_server_streaming(serve_rt):
    """Server-streaming RPC: a method named *stream yields one response
    message per generator item (the gRPC mirror of the HTTP SSE route —
    token streams for LLM serving)."""
    import grpc

    @serve.deployment
    class Tok:
        def stream(self, payload: bytes):
            for i, ch in enumerate(payload.decode().split(",")):
                yield {"i": i, "tok": ch}

        def rawstream(self, payload: bytes):
            yield payload
            yield payload[::-1]

    serve.run(Tok.bind(), name="gen")
    port = serve.start_grpc_ingress(0)
    channel = grpc.insecure_channel(f"127.0.0.1:{port}")

    items = list(channel.unary_stream("/gen/stream")(b"a,b,c", timeout=60))
    assert [json.loads(x)["tok"] for x in items] == ["a", "b", "c"]

    raw = list(channel.unary_stream("/gen/rawstream")(b"xyz", timeout=60))
    assert raw == [b"xyz", b"zyx"]  # bytes pass through unencoded
    channel.close()


def test_grpc_ingress_bounded_admission(ray_tpu_start):
    """Beyond maximum_concurrent_rpcs the server REJECTS with
    RESOURCE_EXHAUSTED instead of stacking blocked threads (the r4
    ingress saturated at 8 blocked threads silently)."""
    import threading
    import time as _time

    import grpc

    @serve.deployment
    class Slow:
        def __call__(self, payload: bytes) -> bytes:
            _time.sleep(3.0)
            return b"done"

    serve.run(Slow.bind(), name="slow")
    port = serve.start_grpc_ingress(0, max_workers=2,
                                    max_concurrent_rpcs=2)
    try:
        channel = grpc.insecure_channel(f"127.0.0.1:{port}")
        call = channel.unary_unary("/slow/__call__")
        results = []

        def fire():
            try:
                call(b"x", timeout=30)
                results.append("ok")
            except grpc.RpcError as e:
                results.append(e.code())

        ts = [threading.Thread(target=fire) for _ in range(5)]
        for t in ts:
            t.start()
            _time.sleep(0.05)  # admit in order
        for t in ts:
            t.join(timeout=60)
        assert grpc.StatusCode.RESOURCE_EXHAUSTED in results, results
        assert results.count("ok") >= 2, results
        channel.close()
    finally:
        serve.stop_grpc_ingress()
        serve.shutdown()


def test_ingress_tls(tmp_path, monkeypatch):
    """With cluster mTLS on, BOTH ingresses serve TLS requiring client
    certificates: a certified gRPC client round-trips (unary and
    streaming), an uncertified one is rejected, and the HTTP proxy
    speaks HTTPS (the ingress must not stay plaintext while the control
    plane is encrypted)."""
    import ssl as _ssl

    import grpc

    from test_tls import _make_certs

    crt, key, ca = _make_certs(tmp_path)
    monkeypatch.setenv("RAY_TPU_TLS_CERT_PATH", crt)
    monkeypatch.setenv("RAY_TPU_TLS_KEY_PATH", key)
    monkeypatch.setenv("RAY_TPU_TLS_CA_PATH", ca)
    from ray_tpu.core.config import reset_config

    reset_config()  # re-read env
    ray_tpu.init(num_cpus=2, system_config={"log_to_driver": False})
    try:
        @serve.deployment
        class Echo:
            def __call__(self, payload: bytes) -> bytes:
                return payload[::-1]

            def stream(self, payload: bytes):
                yield payload
                yield b"end"

        handle = serve.run(Echo.bind(), name="echo")
        gport = serve.start_grpc_ingress(0)
        with open(ca, "rb") as f:
            ca_b = f.read()
        with open(crt, "rb") as f:
            crt_b = f.read()
        with open(key, "rb") as f:
            key_b = f.read()
        creds = grpc.ssl_channel_credentials(
            root_certificates=ca_b, private_key=key_b,
            certificate_chain=crt_b,
        )
        # Cluster certs carry the node IP/hostname? Use the override so
        # verification targets the cert's CN.
        channel = grpc.secure_channel(
            f"127.0.0.1:{gport}", creds,
            options=(("grpc.ssl_target_name_override", "rtpu-node"),),
        )
        call = channel.unary_unary("/echo/__call__")
        assert call(b"abc", timeout=60) == b"cba"
        items = list(channel.unary_stream("/echo/stream")(b"t", timeout=60))
        assert items == [b"t", b"end"]
        channel.close()

        # No client cert -> handshake rejected.
        bad = grpc.secure_channel(
            f"127.0.0.1:{gport}",
            grpc.ssl_channel_credentials(root_certificates=ca_b),
            options=(("grpc.ssl_target_name_override", "rtpu-node"),),
        )
        with pytest.raises(grpc.RpcError):
            bad.unary_unary("/echo/__call__")(b"x", timeout=10)
        bad.close()

        # HTTP proxy serves HTTPS with client-cert verification.
        import http.client

        hport = handle.http_port
        ctx = _ssl.SSLContext(_ssl.PROTOCOL_TLS_CLIENT)
        ctx.load_cert_chain(crt, key)
        ctx.load_verify_locations(ca)
        ctx.check_hostname = False
        conn = http.client.HTTPSConnection(
            "127.0.0.1", hport, context=ctx, timeout=60
        )
        conn.request("GET", "/-/healthz")
        assert conn.getresponse().status == 200
        conn.close()
    finally:
        serve.stop_grpc_ingress()
        serve.shutdown()
        ray_tpu.shutdown()
        reset_config()
