"""C++ client frontend: zero-copy arena puts + JSON task submission.

Ref analogue: the reference's cpp/ worker API tests — a native binary
drives the cluster through the capi channel (core/capi_server.py)
while Python registers the entrypoints it calls.
"""

import os
import subprocess

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEMO = os.path.join(REPO, "build", "rtpu_demo")


def _build_demo() -> bool:
    if os.path.exists(DEMO):
        return True
    proc = subprocess.run(
        ["make", "-C", REPO, "cpp-client"],
        capture_output=True, timeout=180,
    )
    return proc.returncode == 0 and os.path.exists(DEMO)


def test_cpp_client_end_to_end(ray_tpu_start):
    """The native demo connects, puts zero-copy, submits registered
    entrypoints (including one consuming the native put as a bytes
    arg), fetches JSON results and frees its refs."""
    import ray_tpu
    from ray_tpu.core.capi_server import register_entrypoint
    from ray_tpu.core.runtime_context import current_runtime

    if not _build_demo():
        pytest.skip("C++ toolchain unavailable")

    nm = current_runtime()._nm
    if not nm.arena_name:
        pytest.skip("native arena store not active on this node")

    def cpp_add(a, b):
        return a + b

    def cpp_len(blob):
        assert isinstance(blob, bytes), type(blob)
        return len(blob)

    register_entrypoint("cpp_add", cpp_add)
    register_entrypoint("cpp_len", cpp_len)

    proc = subprocess.run(
        [DEMO, nm.session_dir], capture_output=True, text=True,
        timeout=120,
    )
    out = proc.stdout
    assert proc.returncode == 0, (out, proc.stderr)
    for step in ("connect", "put_get", "submit", "submit_ref", "all"):
        assert f"CPPDEMO {step} OK" in out, (step, out, proc.stderr)
    assert "value= 42" in out or "value=42" in out
    # ray_tpu-side sanity: the runtime stayed healthy.
    @ray_tpu.remote
    def ping():
        return "pong"

    assert ray_tpu.get(ping.remote()) == "pong"
