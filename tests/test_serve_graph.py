"""Serve deployment graphs + declarative config deploy.

Ref analogues: serve/_private/deployment_graph_build.py (nested
``.bind()`` handle injection), serve/schema.py + the `serve deploy`
flow (declarative YAML apply).
"""

import sys
import textwrap
import time

import pytest


def test_deployment_graph_nested_bind(ray_tpu_start):
    """Parent.bind(Child.bind()) deploys the child first and hands the
    parent a LIVE handle at construction."""
    import ray_tpu.serve as serve

    @serve.deployment
    class Doubler:
        def __call__(self, x):
            return 2 * x

    @serve.deployment
    class Combiner:
        def __init__(self, doubler, offset):
            self.doubler = doubler
            self.offset = offset

        def __call__(self, x):
            return self.doubler.remote(x).result(timeout=30) + \
                self.offset

    try:
        handle = serve.run(Combiner.bind(Doubler.bind(), 5))
        assert handle.remote(10).result(timeout=60) == 25
        # Child is an ordinary deployment too: scalable + addressable.
        status = serve.status()
        assert "Doubler" in status and "Combiner" in status
        child = serve.get_deployment_handle("Doubler")
        assert child.remote(3).result(timeout=30) == 6
    finally:
        serve.shutdown()


def test_deployment_graph_cycle_rejected(ray_tpu_start):
    import ray_tpu.serve as serve

    @serve.deployment
    class A:
        def __init__(self, other=None):
            pass

    a = A.bind()
    a._init_args = (a,)  # self-cycle
    try:
        with pytest.raises(ValueError, match="cycle"):
            serve.run(a)
    finally:
        serve.shutdown()


def test_parse_config_validation():
    from ray_tpu.serve.schema import parse_config

    apps = parse_config(textwrap.dedent("""
        applications:
          - name: app1
            route_prefix: add
            import_path: mod:dep
            deployments:
              - name: D
                num_replicas: 3
    """))
    assert apps[0].name == "app1"
    assert apps[0].deployments[0].num_replicas == 3

    with pytest.raises(ValueError, match="unknown key"):
        parse_config({"applications": [
            {"import_path": "m:d", "bogus": 1}
        ]})
    with pytest.raises(ValueError, match="import_path required"):
        parse_config({"applications": [{"name": "x"}]})
    with pytest.raises(ValueError, match="duplicate application"):
        parse_config({"applications": [
            {"name": "a", "import_path": "m:d"},
            {"name": "a", "import_path": "m:e"},
        ]})
    with pytest.raises(ValueError, match="must look like"):
        from ray_tpu.serve.schema import import_attr

        import_attr("no_colon_here")


def test_deploy_config_end_to_end(ray_tpu_start, tmp_path):
    """YAML -> import_path -> overrides -> running HTTP app."""
    import urllib.request

    import ray_tpu.serve as serve

    (tmp_path / "demo_serve_app.py").write_text(textwrap.dedent("""
        import ray_tpu.serve as serve

        @serve.deployment
        class Adder:
            def __init__(self, increment):
                self.increment = increment

            def __call__(self, request):
                return {"sum": int(request["x"]) + self.increment}

        graph = Adder.bind(7)
    """))
    sys.path.insert(0, str(tmp_path))
    try:
        routes = serve.deploy_config(textwrap.dedent("""
            applications:
              - name: adder
                route_prefix: add
                import_path: demo_serve_app:graph
                deployments:
                  - name: Adder
                    num_replicas: 2
        """))
        assert routes["adder"]["deployment"] == "Adder"
        port = routes["adder"]["http_port"]
        details = serve.details()
        assert details["Adder"]["target_replicas"] == 2

        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/add",
            data=b'{"x": 35}',
            headers={"Content-Type": "application/json"},
        )
        import json as _json

        body = _json.loads(
            urllib.request.urlopen(req, timeout=30).read()
        )
        # JSON-envelope routes wrap the return value (the ASGI path
        # returns raw bodies; plain deployments use the envelope).
        assert body == {"result": {"sum": 42}}, body
    finally:
        sys.path.remove(str(tmp_path))
        serve.shutdown()


def test_dag_driver_multi_route(ray_tpu_start):
    """DAGDriver: one ingress deployment dispatching to several
    mounted graphs (ref: serve/drivers.py DAGDriver)."""
    import ray_tpu.serve as serve
    from ray_tpu.serve import DAGDriver

    @serve.deployment
    class Adder:
        def __init__(self, inc):
            self.inc = inc

        def __call__(self, x):
            return x + self.inc

    @serve.deployment
    class Multiplier:
        def __call__(self, x):
            return x * 10

    try:
        handle = serve.run(DAGDriver.bind({
            "/add": Adder.bind(5),
            "/mul": Multiplier.bind(),
        }))
        assert handle.remote(7, route="/add").result(timeout=60) == 12
        assert handle.remote(7, route="mul").result(timeout=60) == 70
        status = serve.status()
        assert {"DAGDriver", "Adder", "Multiplier"} <= set(status)
        # Unknown route raises; missing route on multi-mount raises.
        import pytest as _pytest

        with _pytest.raises(Exception, match="no graph mounted"):
            handle.remote(1, route="/nope").result(timeout=60)
        with _pytest.raises(Exception, match="route required"):
            handle.remote(1).result(timeout=60)
    finally:
        serve.shutdown()


def test_dag_driver_single_route_and_adapter(ray_tpu_start):
    """Single mount needs no route; the http adapter shapes the
    payload first."""
    import ray_tpu.serve as serve
    from ray_tpu.serve import DAGDriver

    @serve.deployment
    class Echo:
        def __call__(self, x):
            return {"got": x}

    def double_adapter(req):
        return req * 2

    try:
        handle = serve.run(DAGDriver.bind(
            {"/echo": Echo.bind()}, http_adapter=double_adapter
        ))
        assert handle.remote(21).result(timeout=60) == {"got": 42}
    finally:
        serve.shutdown()
