"""Object spilling + memory-pressure handling (ref analogue:
python/ray/tests/test_object_spilling*.py and the OOM-killer tests over
memory_monitor.h / worker_killing_policy*.h)."""

import os
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.core import runtime_context
from ray_tpu.core.object_store import SpilledLocation

MB = 1024 * 1024


@pytest.fixture
def small_store():
    rt = ray_tpu.init(
        num_cpus=2,
        object_store_memory=8 * MB,
        system_config={
            "num_prestart_workers": 1,
            "gc_grace_period_s": 60.0,
            "refcount_flush_interval_s": 0.1,
        },
    )
    yield rt
    ray_tpu.shutdown()


def test_put_twice_capacity_spills_and_restores(small_store):
    """Puts totalling 2x store capacity all succeed; cold objects spill to
    disk and every value reads back intact."""
    nm = runtime_context.current_runtime()._nm
    refs = []
    for i in range(16):  # 16 x 1 MiB = 2x the 8 MiB capacity
        refs.append(ray_tpu.put(np.full(131072, i, dtype="float64")))
    # Generous: the async spill loop competes for CPU with the rest of a
    # busy test machine.
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and (
        nm._spilling or nm.directory.used_bytes > nm.directory.capacity_bytes
    ):
        time.sleep(0.05)
    # Pressure was relieved by spilling, not refusal.
    assert nm.directory.used_bytes <= nm.directory.capacity_bytes
    spill_dir = nm.spill_manager.spill_dir
    assert os.path.isdir(spill_dir) and len(os.listdir(spill_dir)) > 0
    for i, r in enumerate(refs):
        arr = ray_tpu.get(r, timeout=60)
        assert arr.shape == (131072,)
        assert float(arr[0]) == i and float(arr[-1]) == i


def test_task_results_spill(small_store):
    """Task returns (not just driver puts) participate in spilling."""

    @ray_tpu.remote
    def make(i):
        return np.full(131072, i, dtype="float64")

    refs = [make.remote(i) for i in range(16)]
    out = ray_tpu.get(refs, timeout=120)
    for i, arr in enumerate(out):
        assert float(arr[0]) == i
    nm = runtime_context.current_runtime()._nm
    # Restores for the gets above can transiently exceed capacity until
    # the async spill loop relieves the pressure again.
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and (
        nm._spilling or nm.directory.used_bytes > nm.directory.capacity_bytes
    ):
        time.sleep(0.05)
    assert nm.directory.used_bytes <= nm.directory.capacity_bytes


def test_spilled_object_served_to_peer():
    """A spilled object can still be pulled by another node."""
    from ray_tpu.cluster_utils import Cluster

    c = Cluster(
        head_resources={"CPU": 2},
        system_config={
            "num_prestart_workers": 1,
            "object_store_memory": 4 * MB,
            "gc_grace_period_s": 60.0,
        },
    )
    try:
        nm = runtime_context.current_runtime()._nm
        refs = [
            ray_tpu.put(np.full(131072, i, dtype="float64")) for i in range(8)
        ]
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and (
            nm._spilling
            or not any(
                isinstance(nm.directory.lookup(r.id()), SpilledLocation)
                for r in refs
            )
        ):
            time.sleep(0.05)
        c.add_node(num_cpus=1, resources={"gadget": 1})

        @ray_tpu.remote(resources={"gadget": 1})
        def total(x):
            return float(x.sum())

        # First ref is the coldest -> most likely spilled; sum on the peer.
        assert ray_tpu.get(total.remote(refs[0]), timeout=60) == 0.0
        assert ray_tpu.get(total.remote(refs[7]), timeout=60) == 7.0 * 131072
    finally:
        c.shutdown()


def test_oom_monitor_kills_newest_retriable_task():
    """With an artificially low memory threshold the monitor kills the
    running retriable task; retries exhaust and the error names the OOM
    killer (ref analogue: test_memory_pressure killing policy tests)."""
    rt = ray_tpu.init(
        num_cpus=2,
        system_config={
            "num_prestart_workers": 1,
            "memory_usage_threshold": 0.001,
            "memory_monitor_interval_s": 0.1,
            "default_max_retries": 1,
        },
    )
    try:

        @ray_tpu.remote(max_retries=1)
        def hog():
            time.sleep(30)
            return "survived"

        with pytest.raises(ray_tpu.WorkerCrashedError) as exc_info:
            ray_tpu.get(hog.remote(), timeout=60)
        assert "memory monitor" in str(exc_info.value)
    finally:
        ray_tpu.shutdown()


def test_oom_victim_policy_prefers_retriable():
    """Pure-logic check of the retriable-FIFO victim policy."""
    from ray_tpu.core.node_manager import TaskRecord, WorkerHandle, NodeManager
    from ray_tpu.core.task_spec import TaskSpec

    class _Spec:
        def __init__(self, retries):
            self.retries_left = retries
            self.name = "t"

    class _Rec:
        def __init__(self, retries, created):
            self.spec = _Spec(retries)
            self.created = created

    class _W:
        def __init__(self, rec, actor=None):
            self.state = "busy"
            self.current = rec
            self.actor_id = actor

    workers = {
        1: _W(_Rec(0, 1.0)),
        2: _W(_Rec(2, 2.0)),
        3: _W(_Rec(2, 3.0)),
        4: _W(_Rec(5, 9.0), actor="a"),  # actors are never OOM victims
    }
    fake = type("NM", (), {"_workers": workers})()
    victim = NodeManager._pick_oom_victim(fake)
    assert victim == (workers[3], workers[3].current)
