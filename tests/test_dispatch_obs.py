"""Control-plane dispatch observability tests (ISSUE 17): OpClock
stage accounting and gauge balance, live stage histograms + quantile
derivation through the head TSDB, loop-stall detection (one deduped
WARNING with the stalled thread's stack), slow-op flight-recorder
retention + trace join, the `rtpu rpc` render, loop-monitor detach
hygiene, the log-monitor re-stat fix, and the GIL probe.
"""

import asyncio
import io
import os
import threading
import time

import pytest

import ray_tpu
from ray_tpu.util import dispatch_obs, loop_monitor, profiler
from ray_tpu.util import state as state_api


def _poll(fn, timeout=20.0, interval=0.2):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        out = fn()
        if out:
            return out
        time.sleep(interval)
    return fn()


# ------------------------------------------------------- OpClock units


def test_op_clock_stage_accounting_and_gauge_balance():
    svc = f"t{os.getpid() % 1000}a"
    c = dispatch_obs.op_clock(svc, "ping")
    assert c is not None
    assert dispatch_obs._counts[svc][:2] == [0, 1]  # queued in the backlog
    c.start()
    assert dispatch_obs._counts[svc][:2] == [1, 0]  # started: inflight
    c.handler_done()
    c.done()
    assert dispatch_obs._counts[svc][:2] == [0, 0]
    # The three stage handles exist for the op (reply_send recorded:
    # handler_done was stamped, so the default heuristic says replied).
    assert (svc, "ping") in dispatch_obs._stage_handles
    # done() is idempotent: a double close must not double-decrement.
    c.done()
    assert dispatch_obs._counts[svc][:2] == [0, 0]


def test_op_clock_never_started_leaves_backlog_only():
    svc = f"t{os.getpid() % 1000}b"
    c = dispatch_obs.op_clock(svc, "dead")
    assert dispatch_obs._counts[svc][:2] == [0, 1]
    c.done(replied=False)  # connection died while queued
    assert dispatch_obs._counts[svc][:2] == [0, 0]


def test_op_clock_deferred_restamp_folds_scheduling_into_queue_wait():
    svc = f"t{os.getpid() % 1000}c"
    c = dispatch_obs.op_clock(svc, "bg")
    c.start()
    first = c._t_start
    c.deferred = True
    time.sleep(0.01)
    c.start()  # the bg wrapper re-stamps when the coroutine actually runs
    assert c._t_start > first
    # Re-stamping must not double-count the inflight transition.
    assert dispatch_obs._counts[svc][:2] == [1, 0]
    c.handler_done()
    c.done()
    assert dispatch_obs._counts[svc][:2] == [0, 0]


# --------------------------------------------- live stage histograms


def test_stage_histograms_and_quantiles_live(ray_tpu_start):
    """Real worker traffic lands per-stage histogram series in the head
    TSDB, and the derivation RPC returns a usable handler-stage p99."""

    @ray_tpu.remote
    def f(i):
        return i * 2

    assert ray_tpu.get([f.remote(i) for i in range(40)]) == \
        [i * 2 for i in range(40)]

    rt = ray_tpu_start

    def series_ops():
        got = rt.timeseries_query(
            name="ray_tpu_rpc_server_seconds")["series"]
        ops = {}
        for s in got:
            tags = dict(tuple(kv) for kv in s.get("tags", []))
            ops.setdefault((tags.get("service"), tags.get("op")),
                           set()).add(tags.get("stage"))
        # The OpClock unit tests above flush their synthetic services
        # into the same process registry — only real NM frames count.
        full = {k: v for k, v in ops.items()
                if k[0] == "nm" and {"queue_wait", "handler"} <= v}
        return full or None

    ops = _poll(series_ops)
    assert ops, "no fully-staged nm rpc series reached the TSDB"

    def handler_p99():
        # A prior session's registry (the driver process is shared across
        # tests) re-ingests old nm series as CONSTANT cumulative values:
        # those have a zero windowed delta. Scan for an op with real
        # traffic this session instead of trusting the first discovered.
        for (svc, op) in sorted(ops):
            d = rt.timeseries_query(
                name="ray_tpu_rpc_server_seconds",
                tags={"service": svc, "op": op, "stage": "handler"},
                quantile=0.99, window=120.0).get("derived") or {}
            if d.get("count"):
                return d
        return None

    d = _poll(handler_p99)
    assert d, "no handler-stage series with a nonzero windowed count"
    assert d["count"] > 0
    assert d["quantile"] is not None and d["quantile"] >= 0.0


def test_loop_lag_and_gil_series_live(ray_tpu_start):
    rt = ray_tpu_start

    def lag_loops():
        got = rt.timeseries_query(
            name="ray_tpu_event_loop_lag_seconds")["series"]
        loops = {dict(tuple(kv) for kv in s["tags"]).get("loop")
                 for s in got if s.get("samples")}
        return loops if {"nm", "gcs"} <= loops else None

    assert _poll(lag_loops), "nm/gcs loop-lag series missing from TSDB"
    assert _poll(lambda: rt.timeseries_query(
        name="ray_tpu_gil_wait_ratio")["series"] or None)


# ------------------------------------------------------ loop stalls


def test_loop_stall_emits_one_deduped_warning_with_stack(ray_tpu_start):
    """Block the NM loop past loop_stall_warn_s: the watchdog emits
    exactly ONE WARNING SYSTEM event for the episode (several scan
    ticks pass during the stall — dedup must hold) carrying the
    stalled thread's stack, and the stall is visible in the lag
    gauge."""
    m = loop_monitor.monitors().get("nm")
    assert m is not None and not m.stopped

    stall_s = 1.6  # default loop_stall_warn_s is 1.0
    m.loop.call_soon_threadsafe(time.sleep, stall_s)

    def stall_events():
        evs = [e for e in state_api.list_cluster_events(source="SYSTEM")
               if "loop 'nm' stalled" in e["message"]]
        return evs or None

    evs = _poll(stall_events, timeout=15.0)
    assert evs, "no stall warning reached the head event store"
    # Give any (buggy) duplicate emissions time to flush, then recheck.
    time.sleep(stall_s + 1.0)
    evs = stall_events()
    assert len(evs) == 1, f"stall warning not deduped: {len(evs)} events"
    ev = evs[0]
    assert ev["severity"] == "WARNING"
    cf = ev.get("custom_fields", {})
    assert cf.get("loop") == "nm"
    assert cf.get("overdue_s", 0) >= 1.0
    assert "node_manager" in cf.get("stack", "") or \
        "time.sleep" in cf.get("stack", "") or cf.get("stack")
    # Episode ended (tick resumed): the dedup flag is clear again, so a
    # future stall would warn again.
    assert _poll(lambda: not m.stalled)


def test_live_stall_raises_lag_gauge_while_stalled(ray_tpu_start):
    """The gauge publishes the LIVE overdue time mid-stall (rtpu rpc
    --watch shows the stall as it happens), not only after recovery."""
    from ray_tpu.util.metrics import _registry

    m = loop_monitor.monitors().get("nm")
    assert m is not None
    m.loop.call_soon_threadsafe(time.sleep, 0.9)

    def nm_lag():
        with _registry.lock:
            _, series = _registry.metrics[
                "ray_tpu_event_loop_lag_seconds"]
            return {dict(k).get("loop"): v
                    for k, v in series.items()}.get("nm", 0.0)

    # While the sleep holds the loop, successive watchdog scans publish
    # a growing LIVE overdue value — catch it before the tick resumes.
    max_seen = 0.0
    deadline = time.monotonic() + 0.85
    while time.monotonic() < deadline:
        max_seen = max(max_seen, nm_lag())
        time.sleep(0.05)
    assert max_seen > 0.3, f"live lag gauge peaked at {max_seen}"
    _poll(lambda: not m.stalled)


# ------------------------------------------- slow-op retention + join


def test_slow_op_retained_and_joined_to_traces():
    """An op slower than rpc_slow_op_s lands in the flight recorder
    under reason=slow_op and comes back through the cluster trace
    fan-out (`rtpu trace --slow-ops`)."""
    from ray_tpu.core.runtime_context import current_runtime
    from ray_tpu.util import flight_recorder

    # A near-zero threshold turns ordinary worker traffic into slow
    # ops, exercising the real retention path end to end without
    # needing a deterministically slow handler.
    ray_tpu.init(num_cpus=2, system_config={
        "log_to_driver": False, "rpc_slow_op_s": 0.0002,
    })
    try:
        @ray_tpu.remote
        def f(i):
            return ray_tpu.get(ray_tpu.put(i))

        assert ray_tpu.get([f.remote(i) for i in range(20)]) == \
            list(range(20))

        rows = _poll(lambda: flight_recorder.list_cluster(
            reason="slow_op", limit=50) or None)
        assert rows, "no slow_op records retained"
        assert any(r["name"].startswith("nm.") for r in rows)
        r = next(r for r in rows if r["name"].startswith("nm."))
        assert "handler=" in r.get("detail", "")

        def joined():
            reply = current_runtime().cluster_traces(reason="slow_op")
            found = [r for node in reply.get("nodes", ())
                     for r in node.get("records", ())
                     if r.get("reason") == "slow_op"]
            return found or None

        assert _poll(joined), "cluster trace fan-out missed slow_op rows"
    finally:
        ray_tpu.shutdown()


# ------------------------------------------------------- CLI surface


def test_rtpu_rpc_render(ray_tpu_start, capsys):
    import json as _json

    from ray_tpu.scripts.cli import _render_rpc

    @ray_tpu.remote
    def f():
        return 1

    ray_tpu.get([f.remote() for _ in range(20)])
    rt = ray_tpu_start

    def rendered():
        capsys.readouterr()
        _render_rpc(rt, 120.0, 10)
        text = capsys.readouterr().out
        return text if "SERVICE" in text and "nm" in text else None

    text = _poll(rendered)
    assert text, "rtpu rpc never rendered an op table"
    assert "handler" in text
    assert "loop lag:" in text

    _render_rpc(rt, 120.0, 10, as_json=True)
    blob = _json.loads(capsys.readouterr().out)
    assert blob["ops"] and any(r["service"] == "nm" for r in blob["ops"])
    assert "loop_lag_s" in blob and "gil_wait_ratio" in blob


def test_stack_dump_annotates_loop_threads(ray_tpu_start):
    def annotated():
        stacks = profiler.dump_stacks()
        # Single-node mode runs the GCS on the NM's loop, so the one
        # loop thread carries a merged "gcs+nm" annotation; thread_id
        # is stamped by each monitor's first on-loop tick.
        names = {n for t in stacks
                 for n in (t.get("loop") or "").split("+") if n}
        return stacks if {"nm", "gcs"} <= names else None

    stacks = _poll(annotated)
    assert stacks, "nm/gcs loop threads never annotated in dump_stacks"
    text = profiler.format_stack_text(
        [t for t in stacks if "nm" in (t.get("loop") or "")])
    assert "[loop gcs+nm" in text


# --------------------------------------------------- monitor hygiene


def test_loop_monitor_detach_cancels_pending_tick():
    loop = asyncio.new_event_loop()
    t = threading.Thread(target=loop.run_forever, daemon=True)
    t.start()
    try:
        name = f"t-{os.getpid()}-detach"
        m = loop_monitor.attach(name, loop, interval_s=0.05)
        assert _poll(lambda: m.thread_id is not None, timeout=5.0)
        assert name in loop_monitor.monitors()
        loop_monitor.detach(name)
        assert name not in loop_monitor.monitors()
        # The pending call_later tick is cancelled on the loop: no
        # callback keeps firing after detach.
        assert _poll(lambda: m._handle is None or m._handle.cancelled(),
                     timeout=5.0)
        last = m.last_tick
        time.sleep(0.2)
        assert m.last_tick == last, "tick kept firing after detach"
        # Re-attach under the same name works (idempotence is by name,
        # not forever).
        m2 = loop_monitor.attach(name, loop, interval_s=0.05)
        assert m2 is not m
        loop_monitor.detach(name)
    finally:
        loop.call_soon_threadsafe(loop.stop)
        t.join(timeout=5.0)
        loop.close()


def test_session_shutdown_detaches_nm_and_gcs_monitors():
    ray_tpu.init(num_cpus=1, system_config={"log_to_driver": False})
    assert {"nm", "gcs"} <= set(loop_monitor.monitors())
    ray_tpu.shutdown()
    left = {n for n in ("nm", "gcs") if n in loop_monitor.monitors()}
    assert not left, f"monitors leaked across shutdown: {left}"


# ------------------------------------------------------- GIL probe


def test_gil_monitor_sample_bounds():
    m = profiler.GilMonitor()
    ratio = m.sample_once()
    assert 0.0 <= ratio <= 1.0
    assert m.last_ratio == ratio


# --------------------------------------------------- log monitor fix


def test_log_monitor_skips_unchanged_files_and_handles_rotation(
        tmp_path, monkeypatch):
    from ray_tpu.core.log_monitor import LogMonitor

    logs = tmp_path / "logs"
    logs.mkdir()
    path = logs / "worker-deadbeef.log"
    path.write_bytes(b"first\n")

    out = io.StringIO()
    mon = LogMonitor(str(tmp_path), node_manager=None, out=out)
    mon._poll_once()
    assert "first" in out.getvalue()

    # Steady state: unchanged (mtime, size) means ZERO opens — the fix
    # under test (previously every 200 ms tick re-read bookkeeping and
    # opened every file regardless of activity).
    opens = []
    real_open = open

    def counting_open(*a, **kw):
        opens.append(a[0] if a else kw.get("file"))
        return real_open(*a, **kw)

    monkeypatch.setattr("builtins.open", counting_open)
    mon._poll_once()
    mon._poll_once()
    assert not opens, f"unchanged file re-opened: {opens}"
    monkeypatch.undo()

    # Growth still streams (stat pair changes).
    with real_open(path, "ab") as f:
        f.write(b"second\n")
    mon._poll_once()
    assert "second" in out.getvalue()

    # Rotation/truncate-in-place: smaller size resets the offset and
    # the fresh content streams from the top, with no stale partial.
    mon._partial[str(path)] = b"stale-partial"
    path.write_bytes(b"rot\n")
    mon._poll_once()
    tail = out.getvalue().splitlines()[-1]
    assert tail.endswith("rot")
    assert "stale-partial" not in out.getvalue()
    assert mon._offsets[str(path)] == 4
