"""Smoke tests for the framework microbenchmark harness (ref analogue:
release/microbenchmark running _private/ray_perf.py)."""

import ray_tpu
from ray_tpu.perf import run_cluster_benchmarks, run_microbenchmarks, timeit


def test_timeit_reports_rate():
    name, rate = timeit("noop", lambda: None, repeat=1, min_window_s=0.05)
    assert name == "noop"
    assert rate > 1000  # a no-op must run far faster than 1k ops/s


def test_microbenchmarks_run(ray_tpu_start):
    results = run_microbenchmarks(
        batch=20, payload_mb=1, repeat=1, min_window_s=0.05
    )
    assert len(results) == 7
    for name, rate in results.items():
        assert rate > 0, name
    # Sanity floors: the control plane should do far better than these.
    assert results["single client get calls"] > 50
    assert results["tasks submit+get throughput"] > 20


def test_cluster_transfer_benchmark():
    from ray_tpu.cluster_utils import Cluster

    c = Cluster(
        head_resources={"CPU": 2},
        system_config={"num_prestart_workers": 1},
    )
    try:
        c.add_node(num_cpus=1, resources={"gadget": 1})
        results = run_cluster_benchmarks(
            c, payload_mb=1, repeat=1, min_window_s=0.05
        )
        assert results["cross-node object transfer gigabytes"] > 0
    finally:
        c.shutdown()
