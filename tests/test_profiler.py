"""Cluster-wide profiling & hang-diagnosis plane (ref analogue: `ray
stack` + the dashboard reporter's profile_manager tests): the
dependency-free sampler primitives, folded/speedscope exporters,
cluster-wide stack/profile fan-out over the GCS ProfileService, the
hang/straggler detector's WARNING event, worker activity columns, and
the dashboard/CLI satellites."""

import json
import threading
import time

import pytest

import ray_tpu
from ray_tpu.util import profiler
from ray_tpu.util import state as state_api


def _poll(fn, timeout=15.0, interval=0.2):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        out = fn()
        if out:
            return out
        time.sleep(interval)
    return fn()


# ------------------------------------------------------ sampler primitives


def _busy_marker_fn(stop):
    x = 0
    while not stop.is_set():
        x += 1
    return x


def test_dump_stacks_sees_named_thread():
    stop = threading.Event()
    t = threading.Thread(target=_busy_marker_fn, args=(stop,),
                         name="busy-marker", daemon=True)
    t.start()
    try:
        threads = profiler.dump_stacks()
        names = {th["name"] for th in threads}
        assert "MainThread" in names
        busy = next(th for th in threads if th["name"] == "busy-marker")
        assert any(fr["function"] == "_busy_marker_fn"
                   for fr in busy["frames"])
        # Frames are outermost-first with file/line/function populated.
        assert all({"file", "line", "function"} <= set(fr)
                   for fr in busy["frames"])
        text = profiler.format_stack_text(threads)
        assert "busy-marker" in text and "_busy_marker_fn" in text
    finally:
        stop.set()


def test_sample_produces_collapsed_stacks_for_busy_thread():
    stop = threading.Event()
    t = threading.Thread(target=_busy_marker_fn, args=(stop,),
                         name="busy-sampled", daemon=True)
    t.start()
    try:
        prof = profiler.sample(0.4, hz=200)
    finally:
        stop.set()
    assert prof["samples"] > 0
    assert prof["counts"], "busy thread must yield non-empty counts"
    hits = [s for s in prof["counts"]
            if s.startswith("busy-sampled;") and "_busy_marker_fn" in s]
    assert hits, prof["counts"]
    # Folded text: "stack count" per line, heaviest first.
    folded = profiler.to_folded(prof["counts"])
    first = folded.splitlines()[0].rsplit(" ", 1)
    assert first[1].isdigit()
    assert int(first[1]) == max(prof["counts"].values())


def test_speedscope_export_round_trips_through_json():
    counts = {"main;a.py:f;a.py:g": 7, "main;a.py:f": 3,
              "worker;b.py:h": 2}
    doc = json.loads(json.dumps(profiler.to_speedscope(counts)))
    assert doc["$schema"] == \
        "https://www.speedscope.app/file-format-schema.json"
    frames = doc["shared"]["frames"]
    prof = doc["profiles"][0]
    assert prof["type"] == "sampled"
    assert len(prof["samples"]) == len(prof["weights"]) == 3
    assert sorted(prof["weights"], reverse=True) == prof["weights"]
    assert sum(prof["weights"]) == prof["endValue"] == 12
    for stack_idxs in prof["samples"]:
        for idx in stack_idxs:
            assert 0 <= idx < len(frames)
    # Shared frames dedupe: "a.py:f" appears in two stacks, once here.
    names = [f["name"] for f in frames]
    assert names.count("a.py:f") == 1


def test_task_resource_sampler_and_process_stats():
    s = profiler.TaskResourceSampler().start()
    x = sum(i * i for i in range(200_000))
    assert x > 0
    usage = s.finish()
    assert usage["cpu_s"] >= 0.0
    assert usage["max_rss_bytes"] > 0
    import os

    stats = profiler.process_stats(os.getpid())
    assert stats.get("rss_bytes", 0) > 0
    assert stats.get("cpu_seconds", -1) >= 0
    # A dead pid degrades to an empty dict, never raises.
    assert profiler.process_stats(2 ** 30) == {}


# --------------------------------------------------- cluster fan-out


@pytest.fixture
def hang_cluster():
    """Single-node runtime with a hair-trigger hang detector."""
    rt = ray_tpu.init(
        num_cpus=4,
        system_config={
            "num_prestart_workers": 2,
            "hang_task_warn_s": 0.5,
        },
    )
    yield rt
    ray_tpu.shutdown()


@pytest.fixture
def two_node_cluster():
    from ray_tpu.cluster_utils import Cluster

    c = Cluster(
        head_resources={"CPU": 2},
        system_config={"num_prestart_workers": 1,
                       "default_max_retries": 0},
    )
    c.add_node(num_cpus=1, resources={"gadget": 1})
    yield c
    c.shutdown()


def test_cluster_stacks_two_nodes_head_and_every_worker(two_node_cluster):
    """Acceptance: `rtpu stack` on a 2-node in-process cluster returns
    stack dumps for the head and every live worker."""
    import os as _os

    @ray_tpu.remote(resources={"gadget": 1})
    def remote_pid():
        import os

        return os.getpid()

    @ray_tpu.remote
    def head_pid():
        import os

        return os.getpid()

    rpid = ray_tpu.get(remote_pid.remote(), timeout=60)
    hpid = ray_tpu.get(head_pid.remote(), timeout=60)
    assert rpid != _os.getpid()

    known = {w["pid"] for w in state_api.list_workers()
             if w.get("pid") is not None}
    reply = profiler.cluster_stacks(timeout=10.0)
    assert reply["errors"] == {}
    nodes = reply["nodes"]
    assert len(nodes) == 2
    heads = [n for n in nodes if n["is_head"]]
    assert len(heads) == 1
    # Every node contributes its node-manager process with live threads.
    for n in nodes:
        kinds = [p["kind"] for p in n["procs"]]
        assert "node_manager" in kinds
        for p in n["procs"]:
            assert p["threads"], p
            assert any(t["frames"] for t in p["threads"])
    worker_pids = {p["pid"] for n in nodes for p in n["procs"]
                   if p["kind"] == "worker"}
    # Every live worker answered — including the one on the second node.
    assert known <= worker_pids
    assert rpid in worker_pids and hpid in worker_pids


def test_cluster_profile_speedscope_valid(two_node_cluster):
    """Acceptance: `rtpu profile --seconds 1 --format speedscope` emits
    valid speedscope JSON (same pipeline: cluster_profile → merge →
    to_speedscope)."""

    @ray_tpu.remote
    def warmup():
        return 1

    @ray_tpu.remote
    def burn(seconds):
        end = time.monotonic() + seconds
        x = 0
        while time.monotonic() < end:
            x += 1
        return x

    assert ray_tpu.get(warmup.remote(), timeout=60) == 1
    ref = burn.remote(3.0)
    time.sleep(0.3)  # let the burn frame reach its worker
    reply = profiler.cluster_profile(seconds=1.0, hz=150)
    assert ray_tpu.get(ref, timeout=60) > 0
    assert reply["errors"] == {}
    assert len(reply["nodes"]) == 2
    merged = profiler.merge_cluster_profile(reply)
    assert merged["samples"] > 0
    assert merged["counts"]
    # Keys carry node + process provenance end to end.
    assert all(k.startswith("node:") and ";pid:" in k
               for k in merged["counts"])
    # The burning worker shows up in somebody's samples.
    assert any("burn" in k for k in merged["counts"]), \
        list(merged["counts"])[:10]
    doc = json.loads(json.dumps(profiler.to_speedscope(
        merged["counts"], name="test profile"
    )))
    prof = doc["profiles"][0]
    assert doc["shared"]["frames"] and prof["samples"]
    assert len(prof["samples"]) == len(prof["weights"])
    assert prof["endValue"] == sum(prof["weights"]) > 0


def test_hang_detector_emits_warning_with_stack(hang_cluster):
    """Acceptance: a task exceeding hang_task_warn_s produces a WARNING
    cluster event containing a captured stack."""

    @ray_tpu.remote
    def slow_squat():
        time.sleep(3)
        return 41

    ref = slow_squat.remote()
    ev = _poll(lambda: next(
        (e for e in state_api.list_cluster_events(severity="WARNING")
         if e["source"] == "TASK" and "hang_task_warn_s" in e["message"]
         and "slow_squat" in e["message"]), None))
    assert ev is not None
    cf = ev["custom_fields"]
    assert cf["elapsed_s"] >= 0.5
    assert cf["threshold_s"] == 0.5
    assert cf["stack"], "worker stack must be captured"
    assert "slow_squat" in cf["stack"]
    # The task itself is unharmed — the detector only observes.
    assert ray_tpu.get(ref, timeout=30) == 41
    # One warning per run, not one per sweep.
    time.sleep(1.2)
    warns = [e for e in state_api.list_cluster_events(severity="WARNING")
             if "slow_squat" in e.get("message", "")]
    assert len(warns) == 1


def test_list_workers_carries_current_activity(hang_cluster):

    @ray_tpu.remote
    def slow_visible():
        time.sleep(2)
        return 1

    ref = slow_visible.remote()

    def busy_row():
        rows = [w for w in state_api.list_workers()
                if w.get("current_task") == "slow_visible"]
        return rows[0] if rows else None

    row = _poll(busy_row, timeout=10.0)
    assert row is not None
    assert row["current_task_id"]
    assert row["running_for_s"] >= 0
    # Live /proc stats for the worker process.
    assert row.get("rss_bytes", 0) > 0
    assert row.get("cpu_seconds", -1) >= 0
    assert ray_tpu.get(ref, timeout=30) == 1


def test_terminal_task_record_carries_resource_usage(hang_cluster):

    @ray_tpu.remote
    def crunch():
        return sum(i * i for i in range(400_000))

    assert ray_tpu.get(crunch.remote(), timeout=30) > 0
    row = _poll(lambda: next(
        (t for t in state_api.list_tasks()
         if t.get("retained") and t["name"] == "crunch"), None))
    assert row["cpu_time_s"] is not None and row["cpu_time_s"] >= 0
    assert row["max_rss_bytes"] and row["max_rss_bytes"] > 0


# ------------------------------------------------------ dashboard plane


def test_dashboard_stacks_and_profile_routes(hang_cluster):
    import urllib.error
    import urllib.request

    from ray_tpu import dashboard

    port = dashboard.start_dashboard(port=0)
    try:
        def fetch(path):
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{path}", timeout=60) as r:
                return json.loads(r.read())

        stacks = fetch("/api/stacks")
        assert stacks["nodes"]
        procs = stacks["nodes"][0]["procs"]
        assert any(p["kind"] == "node_manager" for p in procs)

        prof = fetch("/api/profile?seconds=0.3&hz=50")
        assert "counts" in prof and prof["nodes"]

        # Non-numeric query params are a clean 400, not a traceback.
        for bad in ("/api/profile?seconds=abc",
                    "/api/profile?seconds=1&hz=fast"):
            with pytest.raises(urllib.error.HTTPError) as err:
                fetch(bad)
            assert err.value.code == 400
            assert "numeric" in json.loads(err.value.read())["error"]
    finally:
        dashboard.stop_dashboard()


# --------------------------------------------------------- satellites


def test_timeline_deferred_timer_cancelled_on_flush():
    from ray_tpu.core.timeline import TaskEventBuffer

    buf = TaskEventBuffer("t")
    now = time.time()
    buf.record("a", now, now + 0.1)      # immediate flush path
    buf.record("b", now, now + 0.1)      # throttled: arms the timer
    assert buf._timer is not None
    timer = buf._timer
    buf.flush()
    assert buf._timer is None
    assert not timer.is_alive() or timer.finished.is_set()


def test_cmd_memory_sorts_once_and_reports_total(monkeypatch, capsys):
    from ray_tpu.scripts import cli

    census = {
        "nodes": [{
            "node_id": "n1" * 16,
            "used_bytes": 1500, "capacity_bytes": 10_000,
            "spilled_bytes": 0, "inflight_pulls": [],
            "objects": [
                {"object_id": "aa", "size_bytes": 100, "refcount": 1,
                 "state": "in-memory", "owner": "put"},
                {"object_id": "bb", "size_bytes": None, "refcount": 1,
                 "state": "spilled", "owner": ""},
                {"object_id": "cc", "size_bytes": 900, "refcount": 2,
                 "state": "in-memory", "owner": "make"},
                {"object_id": "dd", "size_bytes": 500, "refcount": 1,
                 "state": "in-memory", "owner": "put"},
            ],
        }],
        "errors": {"f0" * 16: "peer unreachable"},
    }

    class _FakeRayTpu:
        @staticmethod
        def shutdown():
            pass

    class _FakeRuntime:
        @staticmethod
        def cluster_objects(limit=10_000):
            return census

    monkeypatch.setattr(cli, "_attached", lambda args: _FakeRayTpu)
    monkeypatch.setattr(
        "ray_tpu.core.runtime_context.current_runtime",
        lambda: _FakeRuntime,
    )

    class _Args:
        limit = 2
        watch = None

    assert cli.cmd_memory(_Args()) == 0
    out = capsys.readouterr().out
    lines = out.splitlines()
    # Sorted by size desc, sliced once to the display limit: the two
    # BIGGEST objects are shown, the rest only count toward TOTAL.
    assert "cc" in lines[1] and "dd" in lines[2]
    assert "aa" not in lines[1] and "bb" not in out
    total_line = next(line for line in lines if "TOTAL" in line)
    # TOTAL covers ALL 4 objects (1500 bytes), not just the 2 shown.
    assert "4 objects" in total_line and "2 shown" in total_line
    assert "1500" in total_line
    # Census enrichment: per-state totals, per-owner aggregation, the
    # shared store footer, and unreachable nodes degrade visibly.
    assert "in-memory: 3 objects" in out
    assert "by owner:" in out and "make=1/" in out
    assert "store:" in out
    assert "node f0f0f0f0: unreachable" in out


def test_cli_stack_and_profile_parsers():
    """The new subcommands parse their documented flags (handlers are
    mocked out so nothing attaches to a cluster)."""
    import unittest.mock as mock

    from ray_tpu.scripts import cli

    with mock.patch.object(cli, "cmd_stack",
                           side_effect=lambda a: 0) as mstack:
        assert cli.main(["stack", "--worker", "abcd",
                         "--timeout", "3", "--json"]) == 0
        ns = mstack.call_args[0][0]
    assert ns.worker == "abcd" and ns.timeout == 3.0 and ns.json

    with mock.patch.object(cli, "cmd_profile",
                           side_effect=lambda a: 0) as mprof:
        assert cli.main(["profile", "--seconds", "1",
                         "--format", "speedscope",
                         "-o", "/tmp/x.json"]) == 0
        ns = mprof.call_args[0][0]
    assert ns.seconds == 1.0 and ns.format == "speedscope"
    assert ns.output == "/tmp/x.json"
