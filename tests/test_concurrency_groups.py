"""Actor concurrency groups + out-of-order execution (VERDICT r3 ask
#8; ref: core_worker/transport/concurrency_group_manager.h,
out_of_order_actor_submit_queue.h)."""

import time

import pytest

import ray_tpu


@pytest.fixture
def rt():
    ray_tpu.init(num_cpus=2, system_config={"log_to_driver": False})
    yield
    ray_tpu.shutdown()


def test_io_group_concurrent_with_busy_compute(rt):
    """The done criterion: a group-annotated actor serves its "io" group
    while a "compute" method is busy. Event-ordered, not wall-clocked:
    crunch() blocks until an io-group call releases it, so peek()
    observing "crunching" (and unblock() succeeding at all) proves the
    io group ran WHILE compute was occupied."""

    @ray_tpu.remote(concurrency_groups={"io": 2, "compute": 1})
    class Worker:
        def __init__(self):
            self.state = "idle"
            self.release = False

        @ray_tpu.method(concurrency_group="compute")
        def crunch(self):
            self.state = "crunching"
            while not self.release:
                time.sleep(0.01)
            self.state = "done"
            return "crunched"

        @ray_tpu.method(concurrency_group="io")
        def peek(self):
            return self.state

        @ray_tpu.method(concurrency_group="io")
        def unblock(self):
            self.release = True
            return True

    w = Worker.remote()
    busy = w.crunch.remote()
    # io calls answer WHILE compute is busy — and observe its state.
    deadline = time.time() + 30
    state = ray_tpu.get(w.peek.remote(), timeout=10)
    while state != "crunching" and time.time() < deadline:
        time.sleep(0.02)
        state = ray_tpu.get(w.peek.remote(), timeout=10)
    assert state == "crunching"
    # crunch can ONLY finish if this io call runs during it.
    assert ray_tpu.get(w.unblock.remote(), timeout=10) is True
    assert ray_tpu.get(busy, timeout=30) == "crunched"


def test_method_options_group_override(rt):
    """.options(concurrency_group=...) routes an unannotated method."""

    @ray_tpu.remote(concurrency_groups={"io": 1})
    class W:
        def __init__(self):
            self.release = False

        def slow_default(self):
            # Blocks the DEFAULT group until an io-group call releases
            # it; if fast() were routed to the default group it would
            # queue behind this forever and the get below would time out.
            while not self.release:
                time.sleep(0.01)
            return "slow"

        def fast(self):
            return "fast"

        def unblock(self):
            self.release = True
            return True

    w = W.remote()
    slow = w.slow_default.remote()
    out = ray_tpu.get(
        w.fast.options(concurrency_group="io").remote(), timeout=10
    )
    assert out == "fast"
    ray_tpu.get(w.unblock.options(concurrency_group="io").remote(),
                timeout=10)
    assert ray_tpu.get(slow, timeout=30) == "slow"


def test_out_of_order_independent_methods(rt):
    """allow_out_of_order + max_concurrency: a later independent call
    completes while an earlier one is still sleeping (submission-order
    commitment relaxed; parallelism still comes from max_concurrency,
    matching the reference's out_of_order_actor_submit_queue)."""

    @ray_tpu.remote(allow_out_of_order=True, max_concurrency=2)
    class OOO:
        def __init__(self):
            self.release = False

        def nap(self):
            # Holds one concurrency slot until unblock() runs; quick()
            # completing at all proves the later call did not wait
            # behind this earlier, still-running one.
            while not self.release:
                time.sleep(0.01)
            return "napped"

        def quick(self):
            return "quick"

        def unblock(self):
            self.release = True
            return True

    a = OOO.remote()
    slow = a.nap.remote()
    assert ray_tpu.get(a.quick.remote(), timeout=10) == "quick"
    assert ray_tpu.get(a.unblock.remote(), timeout=10) is True
    assert ray_tpu.get(slow, timeout=30) == "napped"


def test_out_of_order_concurrency_one_stays_serial(rt):
    """allow_out_of_order with max_concurrency=1 must NOT introduce
    parallel execution — only the ordering commitment is relaxed
    (unguarded actor state stays safe)."""

    @ray_tpu.remote(allow_out_of_order=True)
    class Serial:
        def __init__(self):
            self.n = 0

        def bump(self):
            v = self.n
            time.sleep(0.02)  # interleaving window if parallel
            self.n = v + 1
            return self.n

        def total(self):
            return self.n

    s = Serial.remote()
    refs = [s.bump.remote() for _ in range(20)]
    ray_tpu.get(refs, timeout=60)
    assert ray_tpu.get(s.total.remote(), timeout=10) == 20


def test_default_actor_stays_ordered(rt):
    """Without groups/out-of-order, methods still execute one at a time
    in submission order (the concurrency features are opt-in)."""

    @ray_tpu.remote
    class Ordered:
        def __init__(self):
            self.log = []

        def mark(self, i, sleep=0.0):
            time.sleep(sleep)
            self.log.append(i)
            return i

        def get_log(self):
            return list(self.log)

    o = Ordered.remote()
    o.mark.remote(1, sleep=0.4)
    o.mark.remote(2)
    assert ray_tpu.get(o.get_log.remote(), timeout=15) == [1, 2]
