"""Actor concurrency groups + out-of-order execution (VERDICT r3 ask
#8; ref: core_worker/transport/concurrency_group_manager.h,
out_of_order_actor_submit_queue.h)."""

import time

import pytest

import ray_tpu


@pytest.fixture
def rt():
    ray_tpu.init(num_cpus=2, system_config={"log_to_driver": False})
    yield
    ray_tpu.shutdown()


def test_io_group_concurrent_with_busy_compute(rt):
    """The done criterion: a group-annotated actor serves its "io" group
    while a "compute" method is busy."""

    @ray_tpu.remote(concurrency_groups={"io": 2, "compute": 1})
    class Worker:
        def __init__(self):
            self.state = "idle"

        @ray_tpu.method(concurrency_group="compute")
        def crunch(self, seconds):
            self.state = "crunching"
            time.sleep(seconds)
            self.state = "done"
            return "crunched"

        @ray_tpu.method(concurrency_group="io")
        def peek(self):
            return self.state

    w = Worker.remote()
    busy = w.crunch.remote(3.0)
    time.sleep(0.5)
    # io calls answer WHILE compute is busy — and observe its state.
    t0 = time.time()
    assert ray_tpu.get(w.peek.remote(), timeout=10) == "crunching"
    assert time.time() - t0 < 2.0
    assert ray_tpu.get(busy, timeout=30) == "crunched"


def test_method_options_group_override(rt):
    """.options(concurrency_group=...) routes an unannotated method."""

    @ray_tpu.remote(concurrency_groups={"io": 1})
    class W:
        def __init__(self):
            self.v = 0

        def slow_default(self):
            time.sleep(2.0)
            return "slow"

        def fast(self):
            return "fast"

    w = W.remote()
    slow = w.slow_default.remote()
    time.sleep(0.3)
    t0 = time.time()
    out = ray_tpu.get(
        w.fast.options(concurrency_group="io").remote(), timeout=10
    )
    assert out == "fast" and time.time() - t0 < 1.5
    assert ray_tpu.get(slow, timeout=30) == "slow"


def test_out_of_order_independent_methods(rt):
    """allow_out_of_order + max_concurrency: a later independent call
    completes while an earlier one is still sleeping (submission-order
    commitment relaxed; parallelism still comes from max_concurrency,
    matching the reference's out_of_order_actor_submit_queue)."""

    @ray_tpu.remote(allow_out_of_order=True, max_concurrency=2)
    class OOO:
        def nap(self, s):
            time.sleep(s)
            return "napped"

        def quick(self):
            return "quick"

    a = OOO.remote()
    slow = a.nap.remote(3.0)
    time.sleep(0.3)
    t0 = time.time()
    assert ray_tpu.get(a.quick.remote(), timeout=10) == "quick"
    assert time.time() - t0 < 2.0  # did not wait behind nap()
    assert ray_tpu.get(slow, timeout=30) == "napped"


def test_out_of_order_concurrency_one_stays_serial(rt):
    """allow_out_of_order with max_concurrency=1 must NOT introduce
    parallel execution — only the ordering commitment is relaxed
    (unguarded actor state stays safe)."""

    @ray_tpu.remote(allow_out_of_order=True)
    class Serial:
        def __init__(self):
            self.n = 0

        def bump(self):
            v = self.n
            time.sleep(0.02)  # interleaving window if parallel
            self.n = v + 1
            return self.n

        def total(self):
            return self.n

    s = Serial.remote()
    refs = [s.bump.remote() for _ in range(20)]
    ray_tpu.get(refs, timeout=60)
    assert ray_tpu.get(s.total.remote(), timeout=10) == 20


def test_default_actor_stays_ordered(rt):
    """Without groups/out-of-order, methods still execute one at a time
    in submission order (the concurrency features are opt-in)."""

    @ray_tpu.remote
    class Ordered:
        def __init__(self):
            self.log = []

        def mark(self, i, sleep=0.0):
            time.sleep(sleep)
            self.log.append(i)
            return i

        def get_log(self):
            return list(self.log)

    o = Ordered.remote()
    o.mark.remote(1, sleep=0.4)
    o.mark.remote(2)
    assert ray_tpu.get(o.get_log.remote(), timeout=15) == [1, 2]
