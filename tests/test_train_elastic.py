"""Elastic gang lifecycle: crash-safe checkpoint commit protocol,
supervisor-driven gang abort/restart (dead + hung ranks, chaos
train_worker/checkpoint_io faults), and drain-aware cooperative
preemption composed into Cluster.rolling_restart()."""

import os
import threading
import time

import pytest

import ray_tpu
from ray_tpu import train as rt_train
from ray_tpu.train import (
    Checkpoint,
    CheckpointConfig,
    FailureConfig,
    JaxTrainer,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.train.checkpoint import (
    COMMIT_MANIFEST,
    CheckpointManager,
    latest_committed,
)
from ray_tpu.util import faults


# --------------------------------------------------------------- helpers


def _make_committed(path, step, value=1.0):
    import jax.numpy as jnp

    return Checkpoint.from_pytree(
        {"w": jnp.asarray([value]), "step": jnp.asarray(step)},
        str(path), metadata={"step": step}, step=step, world_size=1,
    )


def _train_events(reason=None, timeout=5.0):
    """TRAIN cluster events (polling past the event ring's flush
    latency); optionally filtered on custom_fields.reason."""
    from ray_tpu.util.state import list_cluster_events

    deadline = time.time() + timeout
    while True:
        evts = [e for e in list_cluster_events(source="TRAIN")
                if reason is None
                or (e.get("custom_fields") or {}).get("reason") == reason]
        if evts or time.time() >= deadline:
            return evts
        time.sleep(0.1)


def _arm(specs):
    from ray_tpu.core.runtime_context import current_runtime

    nm = current_runtime()._nm
    return nm.call_sync(nm._gcs.chaos_arm(specs), timeout=30)


# ------------------------------------------------- commit protocol units


def test_from_pytree_commits_atomically(tmp_path):
    ckpt = _make_committed(tmp_path / "ck", step=7, value=3.0)
    assert ckpt.is_committed()
    assert os.path.exists(os.path.join(ckpt.path, COMMIT_MANIFEST))
    manifest = ckpt.manifest()
    assert manifest["step"] == 7
    assert manifest["world_size"] == 1
    assert manifest["files"], "manifest must list the payload files"
    # Metadata rides inside the atomic commit.
    assert ckpt.metadata() == {"step": 7}
    # No staging orphans survive a successful commit.
    assert not [n for n in os.listdir(tmp_path) if n.startswith(".tmp-")]
    import numpy as np

    restored = ckpt.as_pytree()
    np.testing.assert_allclose(np.asarray(restored["w"]), [3.0])


def test_failed_save_leaves_nothing_visible(tmp_path):
    """A checkpoint_io fault mid-save must leave NO directory at the
    target path and no half-committed state a restore could pick up —
    the crash-mid-save that used to poison 'latest'."""
    faults.apply_plan([{"point": "checkpoint_io", "mode": "always",
                        "match": {"op": "save"}}])
    try:
        with pytest.raises(faults.InjectedFault):
            _make_committed(tmp_path / "ck", step=1)
    finally:
        faults.clear()
    assert not os.path.exists(tmp_path / "ck")
    assert latest_committed(str(tmp_path)) is None


def test_restore_falls_back_past_corrupt_and_uncommitted(tmp_path):
    _make_committed(tmp_path / "checkpoint_000001", step=1, value=1.0)
    good = _make_committed(tmp_path / "checkpoint_000002", step=2, value=2.0)
    corrupt = _make_committed(tmp_path / "checkpoint_000003", step=3)
    uncommitted = _make_committed(tmp_path / "checkpoint_000004", step=4)

    # Corrupt the newest-but-one: truncate a manifest-listed file.
    rel = next(r for r in corrupt.manifest()["files"]
               if r != COMMIT_MANIFEST)
    with open(os.path.join(corrupt.path, rel), "w") as f:
        f.write("")
    assert not corrupt.is_committed()
    # And strip the newest's commit marker entirely.
    os.remove(os.path.join(uncommitted.path, COMMIT_MANIFEST))
    # A stale staging dir must be ignored too.
    os.makedirs(tmp_path / ".tmp-checkpoint_000005-dead")

    found = latest_committed(str(tmp_path))
    assert found is not None and found.path == good.path
    import numpy as np

    np.testing.assert_allclose(
        np.asarray(found.as_pytree()["w"]), [2.0])


def test_manager_retention_edge_cases(tmp_path):
    # Score ties at num_to_keep=2: the protected best (first maximal)
    # and the newest committed survive; the unprotected middle goes.
    m = CheckpointManager(str(tmp_path / "a"), num_to_keep=2,
                          score_attribute="acc", score_order="max")
    cks = [_make_committed(tmp_path / "a" / f"ck{i}", step=i)
           for i in range(3)]
    for i, ck in enumerate(cks):
        m.register(ck, {"acc": 0.5}, step=i)
    assert not os.path.exists(cks[1].path)
    assert os.path.exists(cks[0].path) and os.path.exists(cks[2].path)

    # Missing score attribute falls back to recency retention.
    m2 = CheckpointManager(str(tmp_path / "b"), num_to_keep=1,
                           score_attribute="absent", score_order="max")
    b0 = _make_committed(tmp_path / "b" / "ck0", step=0)
    b1 = _make_committed(tmp_path / "b" / "ck1", step=1)
    m2.register(b0, {"loss": 1.0}, step=0)
    m2.register(b1, {"loss": 0.5}, step=1)
    assert not os.path.exists(b0.path)
    assert m2.latest.path == b1.path

    # num_to_keep=1 with a scored-worse newcomer: BOTH survive — the
    # best entry is the Result's checkpoint, the newest committed is
    # the restart source; budget overshoots rather than delete either.
    m3 = CheckpointManager(str(tmp_path / "c"), num_to_keep=1,
                           score_attribute="acc", score_order="max")
    c0 = _make_committed(tmp_path / "c" / "ck0", step=0)
    c1 = _make_committed(tmp_path / "c" / "ck1", step=1)
    m3.register(c0, {"acc": 0.9}, step=0)
    m3.register(c1, {"acc": 0.1}, step=1)
    assert os.path.exists(c0.path) and os.path.exists(c1.path)
    assert m3.best.path == c0.path
    assert m3.latest_committed.path == c1.path


def test_prune_never_deletes_only_committed(tmp_path):
    """Uncommitted newer checkpoints never justify deleting the
    committed entry a resuming worker may be restoring from."""
    m = CheckpointManager(str(tmp_path), num_to_keep=1)
    committed = _make_committed(tmp_path / "ck0", step=0)
    m.register(committed, {}, step=0)
    for i in (1, 2):
        p = tmp_path / f"ck{i}"
        os.makedirs(p)
        with open(p / "payload", "w") as f:
            f.write("not committed")
        m.register(Checkpoint(str(p)), {}, step=i)
    # Over budget (3 entries, keep 1) but nothing newer has COMMITTED:
    # the committed entry must survive, and latest must point at it.
    assert os.path.exists(committed.path)
    assert m.latest_committed.path == committed.path
    assert m.latest.path == committed.path
    # Once a newer checkpoint commits, the old entries become deletable.
    newer = _make_committed(tmp_path / "ck3", step=3)
    m.register(newer, {}, step=3)
    assert os.path.exists(newer.path)
    assert not os.path.exists(committed.path)
    assert len(m._entries) == 1


# ------------------------------------------------------ supervisor (gang)


def _make_elastic_loop():
    """Factory: the returned closure pickles BY VALUE (a module-level
    function in a tests file would pickle by reference to a module the
    worker processes cannot import)."""

    def _elastic_loop(config):
        """Deterministic resumable loop: w += 1 per step, committed
        checkpoint every step (rank 0), optional crash/hang injection."""
        import os as _os
        import time as _time

        import jax.numpy as jnp

        from ray_tpu import train as _train
        from ray_tpu.train import Checkpoint as _Ckpt

        sess = _train.get_session()
        start = sess.get_checkpoint()
        if start is not None:
            state = start.as_pytree()
            w = float(jnp.asarray(state["w"])[0])
            start_step = int(state["step"]) + 1
        else:
            w, start_step = 0.0, 0
        total = config.get("steps", 4)
        for step in range(start_step, total):
            if sess.preemption_requested():
                break
            marker = config.get("crash_marker")
            if (marker and step == config.get("crash_step")
                    and sess.world_rank == config.get("crash_rank", 0)
                    and not _os.path.exists(marker)):
                open(marker, "w").close()
                if config.get("crash_kind") == "exit":
                    _os._exit(1)
                elif config.get("crash_kind") == "hang":
                    _time.sleep(600)
            w += 1.0
            ckpt = None
            if sess.world_rank == 0:
                ckpt = _Ckpt.from_pytree(
                    {"w": jnp.asarray([w]), "step": jnp.asarray(step)},
                    sess.checkpoint_dir(step),
                    step=step, world_size=sess.world_size,
                )
            _train.report({"step": step, "w": w}, checkpoint=ckpt)
            _time.sleep(config.get("step_sleep", 0.0))

    return _elastic_loop


def test_gang_restart_on_dead_rank(ray_tpu_start, tmp_path):
    """Rank 0 of a gang=2 dies hard (os._exit) mid-run: the supervisor
    aborts the whole gang, restarts from the last committed checkpoint,
    and the run completes with the exact resumed state."""
    marker = str(tmp_path / "crashed")
    result = JaxTrainer(
        _make_elastic_loop(),
        train_loop_config={"steps": 4, "crash_marker": marker,
                           "crash_step": 2, "crash_rank": 0,
                           "crash_kind": "exit"},
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(
            storage_path=str(tmp_path / "run"),
            failure_config=FailureConfig(max_failures=1),
        ),
    ).fit()
    assert result.error is None, result.error
    assert os.path.exists(marker)
    assert result.metrics["step"] == 3
    assert result.metrics["w"] == 4.0  # resumed, not recomputed
    assert result.checkpoint is not None and result.checkpoint.is_committed()
    assert _train_events(), "expected TRAIN cluster events"


@pytest.mark.slow
def test_gang_abort_on_hung_rank(ray_tpu_start, tmp_path):
    """A rank that hangs between collectives (process alive, heartbeat
    flowing, step counter frozen while the gang moves on) is detected
    within train_rank_timeout_s and the gang is killed + restarted —
    not left to wait out a collective timeout."""
    from ray_tpu.core.config import get_config

    cfg = get_config()
    old = cfg.train_rank_timeout_s
    cfg.train_rank_timeout_s = 4.0
    marker = str(tmp_path / "hung")
    t0 = time.monotonic()
    try:
        result = JaxTrainer(
            _make_elastic_loop(),
            train_loop_config={"steps": 4, "crash_marker": marker,
                               "crash_step": 1, "crash_rank": 0,
                               "crash_kind": "hang"},
            scaling_config=ScalingConfig(num_workers=2),
            run_config=RunConfig(
                storage_path=str(tmp_path / "run"),
                failure_config=FailureConfig(max_failures=1),
            ),
        ).fit()
    finally:
        cfg.train_rank_timeout_s = old
    elapsed = time.monotonic() - t0
    assert result.error is None, result.error
    assert result.metrics["step"] == 3
    # The hung rank slept 600s; finishing fast proves the prompt kill.
    assert elapsed < 120, f"gang waited out the hang: {elapsed:.0f}s"
    evts = _train_events(reason="hang")
    assert evts, "expected a WARNING TRAIN gang-abort event (hang)"


@pytest.mark.slow
def test_chaos_kill_mid_step_matches_uninterrupted(ray_tpu_start, tmp_path):
    """THE acceptance run: gang=2 multi-process JaxTrainer, rank 1
    killed mid-step via the train_worker fault point, restart from the
    last committed checkpoint — final loss/step trajectory matches an
    uninterrupted run's."""
    from ray_tpu.core.runtime_context import current_runtime

    steps = 8
    baseline = JaxTrainer(
        _make_elastic_loop(),
        train_loop_config={"steps": steps, "step_sleep": 0.15},
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(storage_path=str(tmp_path / "base")),
    ).fit()
    assert baseline.error is None, baseline.error

    # Chaos run: discover the attempt's run id from its heartbeat keys,
    # then arm a once-spec scoped to {rank 1, THAT run} — rank 1's
    # second matching report raises an injected ConnectionError (a rank
    # killed mid-step). The restarted attempt has a fresh run id, so
    # the spec can never re-fire against it.
    holder = {}
    rt = current_runtime()
    known = {k.split("/")[1] for k in rt.kv_keys("__train__/")
             if len(k.split("/")) >= 2}

    def run_chaotic():
        holder["result"] = JaxTrainer(
            _make_elastic_loop(),
            train_loop_config={"steps": steps, "step_sleep": 0.15},
            scaling_config=ScalingConfig(num_workers=2),
            run_config=RunConfig(
                storage_path=str(tmp_path / "chaos"),
                failure_config=FailureConfig(max_failures=1),
            ),
        ).fit()

    t = threading.Thread(target=run_chaotic, daemon=True)
    t.start()
    run_id = None
    deadline = time.time() + 30
    while run_id is None and time.time() < deadline:
        for key in rt.kv_keys("__train__/"):
            parts = key.split("/")
            if len(parts) >= 2 and parts[1] and parts[1] not in known:
                run_id = parts[1]
                break
        time.sleep(0.05)
    assert run_id, "no train run appeared in KV"
    try:
        _arm([{"point": "train_worker", "mode": "once", "n": 2,
               "match": {"rank": "1", "run": run_id}}])
        t.join(timeout=150)
    finally:
        _arm([])
        faults.clear()
    assert not t.is_alive(), "chaotic fit did not finish"
    chaotic = holder["result"]
    assert chaotic.error is None, chaotic.error
    assert chaotic.metrics["step"] == baseline.metrics["step"]
    assert chaotic.metrics["w"] == baseline.metrics["w"]
    assert chaotic.checkpoint is not None and chaotic.checkpoint.is_committed()
    # The injected kill is observable end to end: CHAOS firing + TRAIN
    # restart events.
    assert _train_events(), "expected TRAIN restart events"


def test_chaos_checkpoint_io_falls_back_to_previous_commit(
        ray_tpu_start, tmp_path):
    """A checkpoint_io fault during save crashes the attempt; the gang
    restarts from the PREVIOUS committed checkpoint (the torn save
    never became 'latest') and completes."""
    # Fires on the 4th save (step 3) of the single-rank run: committed
    # steps 0-2 exist, so the restart resumes at step 3 and the fresh
    # process makes only 2 more saves — below the once-spec's threshold.
    _arm([{"point": "checkpoint_io", "mode": "once", "n": 4,
           "match": {"op": "save"}}])
    try:
        result = JaxTrainer(
            _make_elastic_loop(),
            train_loop_config={"steps": 5},
            scaling_config=ScalingConfig(num_workers=1),
            run_config=RunConfig(
                storage_path=str(tmp_path / "run"),
                failure_config=FailureConfig(max_failures=1),
            ),
        ).fit()
    finally:
        _arm([])
        faults.clear()
    assert result.error is None, result.error
    assert result.metrics["step"] == 4
    assert result.metrics["w"] == 5.0
    final = latest_committed(str(tmp_path / "run"))
    assert final is not None and final.manifest()["step"] == 4


def test_preemption_signal_surfaces_in_session(ray_tpu_start):
    from ray_tpu.core import preemption
    from ray_tpu.train.session import TrainSession

    sess = TrainSession(run_id="t1", world_rank=0, world_size=1,
                        storage_dir="/tmp", start_checkpoint=None)
    try:
        assert sess.preemption is None
        preemption.signal_local_drain("abcd1234")
        sig = sess.preemption
        assert sig is not None and sig.node_id == "abcd1234"
        assert sess.preemption_requested()
        # The gang-wide KV flag went up for the other ranks.
        other = TrainSession(run_id="t1", world_rank=1, world_size=2,
                             storage_dir="/tmp", start_checkpoint=None)
        # Clear the (process-local) drain flag so `other` exercises the
        # gang-wide KV path, not its own local branch.
        preemption.clear_local_drain()
        deadline = time.time() + 5
        got = None
        while time.time() < deadline and got is None:
            other._preempt_checked = 0.0
            got = other.preemption
        assert got is not None and got.rank == 0
        # Aborted drain (node_undrain): the raising rank retracts the
        # gang flag and every rank's next poll sees the rollback — a
        # rolled-back drain must not cost a whole-gang restart.
        assert sess.preemption is None  # rank 0: local cleared -> retract
        other._preempt_checked = 0.0
        assert other.preemption is None
    finally:
        preemption.clear_local_drain()


# ------------------------------------------- drain + rolling restart e2e


@pytest.mark.slow
def test_rolling_restart_under_active_fit_loses_at_most_one_step():
    """ROADMAP item 3's second half: a rolling node replacement under an
    active JaxTrainer.fit() — the gang sees node_draining, checkpoints
    at the next step boundary, surrenders the node, and restarts on the
    replacement, losing at most one step of work."""
    from ray_tpu.cluster_utils import Cluster

    with Cluster(head_resources={"CPU": 2}) as cluster:
        cluster.add_node(num_cpus=4, resources={"trainer": 4})
        steps = 24
        inner = _make_elastic_loop()

        def loop(config):
            inner({"steps": 24, "step_sleep": 0.6})

        holder = {}

        def run_fit():
            holder["result"] = JaxTrainer(
                loop,
                train_loop_config={},
                scaling_config=ScalingConfig(
                    num_workers=2,
                    resources_per_worker={"CPU": 1, "trainer": 1},
                ),
                run_config=RunConfig(
                    name="rolling-fit",
                    failure_config=FailureConfig(max_failures=0),
                ),
            ).fit()

        t = threading.Thread(target=run_fit, daemon=True)
        t.start()
        # Let the gang make some progress, then replace its node WHILE
        # the loop is still running (the whole point of the test).
        time.sleep(5.0)
        replaced = cluster.rolling_restart()
        assert len(replaced) == 1
        t.join(timeout=180)
        assert not t.is_alive(), "fit() did not finish after the roll"
        result = holder["result"]
        assert result.error is None, result.error
        assert result.metrics["step"] == steps - 1
        # Deterministic loop: w == step+1 everywhere proves resume-from-
        # checkpoint; max one step re-executed == at most one step lost.
        history = result.metrics_history
        steps_seen = [m["step"] for m in history]
        assert all(m["w"] == m["step"] + 1.0 for m in history)
        dupes = len(steps_seen) - len(set(steps_seen))
        assert dupes <= 1, f"lost more than one step: {steps_seen}"
        evts = _train_events()
        assert any("preempt" in e["message"] for e in evts), evts
