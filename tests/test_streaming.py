"""Streaming generator tests (ref analogue:
python/ray/tests/test_streaming_generator.py)."""

import time

import pytest

import ray_tpu


def test_streaming_generator_basic(ray_tpu_start):
    @ray_tpu.remote(num_returns="streaming")
    def gen(n):
        for i in range(n):
            yield i * 10

    refs = list(gen.remote(5))
    assert [ray_tpu.get(r) for r in refs] == [0, 10, 20, 30, 40]


def test_streaming_yields_before_completion(ray_tpu_start):
    """Items are consumable while the producer is still running."""

    @ray_tpu.remote
    def warm():
        return 1

    ray_tpu.get(warm.remote())

    @ray_tpu.remote(num_returns="streaming")
    def slow_gen():
        for i in range(4):
            time.sleep(0.3)
            yield i

    t0 = time.monotonic()
    stamps = []
    for ref in slow_gen.remote():
        stamps.append(time.monotonic() - t0)
        ray_tpu.get(ref)
    # First item arrives ~0.3s in, not at the ~1.2s completion.
    assert stamps[0] < stamps[-1] - 0.5, stamps


def test_streaming_generator_error_propagates(ray_tpu_start):
    @ray_tpu.remote(num_returns="streaming")
    def bad():
        yield 1
        raise ValueError("stream broke")

    vals = []
    with pytest.raises(ValueError, match="stream broke"):
        for r in bad.remote():
            vals.append(ray_tpu.get(r))
    assert vals == [1]


def test_streaming_actor_method(ray_tpu_start):
    @ray_tpu.remote
    class Producer:
        def chunks(self, n):
            for i in range(n):
                yield {"chunk": i}

    p = Producer.remote()
    gen = p.chunks.options(num_returns="streaming").remote(3)
    assert [ray_tpu.get(r)["chunk"] for r in gen] == [0, 1, 2]


def test_streaming_empty_generator(ray_tpu_start):
    @ray_tpu.remote(num_returns="streaming")
    def empty():
        if False:
            yield 1

    assert list(empty.remote()) == []
