"""Streaming generator tests (ref analogue:
python/ray/tests/test_streaming_generator.py)."""

import time

import pytest

import ray_tpu


def test_streaming_generator_basic(ray_tpu_start):
    @ray_tpu.remote(num_returns="streaming")
    def gen(n):
        for i in range(n):
            yield i * 10

    refs = list(gen.remote(5))
    assert [ray_tpu.get(r) for r in refs] == [0, 10, 20, 30, 40]


def test_streaming_yields_before_completion(ray_tpu_start):
    """Items are consumable while the producer is still running."""

    @ray_tpu.remote
    def warm():
        return 1

    ray_tpu.get(warm.remote())

    @ray_tpu.remote(num_returns="streaming")
    def slow_gen():
        for i in range(4):
            time.sleep(0.3)
            yield i

    t0 = time.monotonic()
    stamps = []
    for ref in slow_gen.remote():
        stamps.append(time.monotonic() - t0)
        ray_tpu.get(ref)
    # First item arrives ~0.3s in, not at the ~1.2s completion.
    assert stamps[0] < stamps[-1] - 0.5, stamps


def test_streaming_generator_error_propagates(ray_tpu_start):
    @ray_tpu.remote(num_returns="streaming")
    def bad():
        yield 1
        raise ValueError("stream broke")

    vals = []
    with pytest.raises(ValueError, match="stream broke"):
        for r in bad.remote():
            vals.append(ray_tpu.get(r))
    assert vals == [1]


def test_streaming_actor_method(ray_tpu_start):
    @ray_tpu.remote
    class Producer:
        def chunks(self, n):
            for i in range(n):
                yield {"chunk": i}

    p = Producer.remote()
    gen = p.chunks.options(num_returns="streaming").remote(3)
    assert [ray_tpu.get(r)["chunk"] for r in gen] == [0, 1, 2]


def test_streaming_empty_generator(ray_tpu_start):
    @ray_tpu.remote(num_returns="streaming")
    def empty():
        if False:
            yield 1

    assert list(empty.remote()) == []


def test_generator_del_on_node_manager_loop_does_not_deadlock(
        ray_tpu_start):
    """Regression: gc can fire ObjectRefGenerator.__del__ on ANY
    thread — including the node-manager event loop (observed mid-frame
    pickling). The old inline cleanup issued a blocking call_sync back
    onto that same loop and froze the entire runtime; cleanup now runs
    on a detached thread, so the loop must stay responsive."""
    import threading

    from ray_tpu.core.runtime_context import current_runtime

    @ray_tpu.remote(num_returns="streaming")
    def gen():
        for i in range(6):
            yield i

    g = gen.remote()
    assert ray_tpu.get(next(g)) == 0
    assert ray_tpu.get(next(g)) == 1

    nm = current_runtime()._nm
    ran = threading.Event()

    def fire_del_on_loop():
        try:
            g.__del__()  # simulate gc running on the loop thread
        finally:
            ran.set()

    nm._loop.call_soon_threadsafe(fire_del_on_loop)
    assert ran.wait(timeout=10), "__del__ blocked the NM loop"
    # The loop survived: control-plane ops still complete.
    import ray_tpu as rt

    assert rt.kv_put("post_del_probe", b"ok")
    assert rt.kv_get("post_del_probe") == b"ok"

    @ray_tpu.remote
    def ping():
        return 41

    assert rt.get(ping.remote(), timeout=30) == 41
