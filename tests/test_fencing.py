"""Split-brain fencing: membership epochs, actor incarnations, and
zombie-node self-termination under asymmetric partitions.

The acceptance scenario is the one fencing exists for: a node whose
heartbeat is (stickily) partitioned while its peer/direct planes stay
healthy. Without fencing, a caller with a cached direct endpoint keeps
executing calls on the stale incarnation while the cluster restarts
the actor elsewhere — double execution, lost updates, stale
sealed-object locations on heal. With fencing: the GCS fences the node
at a new membership epoch, the caller's channels are torn down,
in-flight calls bound to the fenced incarnation are refused (never
re-executed into the new incarnation), fresh calls land on the
restarted actor, and the zombie self-terminates its workers before
rejoining as a fresh incarnation.
"""

import threading
import time
import uuid

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.util import faults
from ray_tpu.util import state as state_api


def _nm():
    from ray_tpu.core.runtime_context import current_runtime

    return current_runtime()._nm


def _arm(specs):
    nm = _nm()
    return nm.call_sync(nm._gcs.chaos_arm(specs), timeout=30)


def _node_events(needle, timeout=10.0):
    deadline = time.time() + timeout
    while True:
        evts = [e for e in state_api.list_cluster_events(source="NODE")
                if needle in e["message"]]
        if evts or time.time() >= deadline:
            return evts
        time.sleep(0.1)


# ------------------------------------------------------------- unit-ish


def test_nodes_surface_epoch_and_incarnation(ray_tpu_start):
    rows = ray_tpu.nodes()
    assert rows, rows
    for r in rows:
        assert int(r.get("Incarnation") or 0) >= 1, r
        assert int(r.get("Epoch") or 0) >= 1, r  # registration bumped it


def test_actor_incarnation_rides_resolution_and_bumps_on_restart(
        ray_tpu_start):
    """The direct-endpoint descriptor carries the GCS-assigned actor
    incarnation, and a restart mints a NEW one — so a channel dialed
    from a pre-restart resolution can never handshake into the
    restarted actor (the worker refuses the stale ``inc``)."""
    from ray_tpu.core import runtime_context

    @ray_tpu.remote(max_restarts=1)
    class A:
        def pid(self):
            import os

            return os.getpid()

    a = A.remote()
    runtime = runtime_context.current_runtime()
    key = a.actor_id.binary()
    deadline = time.time() + 20
    while time.time() < deadline:
        pid = ray_tpu.get(a.pid.remote(), timeout=30)
        st = runtime._direct_states.get(key)
        if st is not None and st["status"] == "ready":
            break
        time.sleep(0.05)
    else:
        raise AssertionError("direct channel never engaged")
    first_inc = st["chan"].incarnation
    assert first_inc >= 1

    # Kill the actor's worker: the actor restarts (same node) and the
    # next resolution must name a HIGHER incarnation.
    import os as _os
    import signal as _signal

    _os.kill(pid, _signal.SIGKILL)
    deadline = time.time() + 30
    new_inc = None
    while time.time() < deadline:
        try:
            ray_tpu.get(a.pid.remote(), timeout=30)
        except Exception:
            time.sleep(0.2)
            continue
        st = runtime._direct_states.get(key)
        chan = st.get("chan") if st else None
        if chan is not None and chan.alive and st["status"] == "ready":
            new_inc = chan.incarnation
            break
        time.sleep(0.1)
    assert new_inc is not None and new_inc > first_inc, (
        first_inc, new_inc
    )


def test_worker_refuses_stale_incarnation_hello(ray_tpu_start):
    """Dialing an actor's endpooint with a stale incarnation in the
    hello is refused (the fencing guarantee at the handshake)."""
    from ray_tpu.core import runtime_context
    from ray_tpu.core.runtime import _DirectChannel

    @ray_tpu.remote
    class A:
        def ping(self):
            return "ok"

    a = A.remote()
    runtime = runtime_context.current_runtime()
    key = a.actor_id.binary()
    deadline = time.time() + 20
    while time.time() < deadline:
        ray_tpu.get(a.ping.remote(), timeout=30)
        st = runtime._direct_states.get(key)
        if st is not None and st["status"] == "ready":
            break
        time.sleep(0.05)
    else:
        raise AssertionError("direct channel never engaged")
    desc = dict(st["chan"].desc)
    assert int(desc.get("inc") or 0) >= 1
    stale = dict(desc)
    stale["inc"] = int(desc["inc"]) + 7  # an incarnation that never ran
    with pytest.raises(ConnectionError, match="incarnation"):
        _DirectChannel(runtime, a.actor_id, stale)


# ------------------------------------------------- acceptance scenario


def test_asymmetric_partition_zero_double_execution_and_heal():
    """ISSUE 15 acceptance: heartbeat partitioned (sticky) on the
    actor's node, peer/direct plane healthy. The GCS fences the node,
    the actor restarts on a surviving node, and a pipelined caller
    observes ZERO double-executions and ZERO stale-incarnation results
    (fenced in-flight calls are refused, fresh calls land on the new
    incarnation). On heal the zombie self-terminates its workers and
    re-registers as a fresh node incarnation."""
    c = Cluster(
        head_resources={"CPU": 2},
        system_config={
            "num_prestart_workers": 0,
            "heartbeat_interval_s": 0.2,
            "gcs_health_check_period_s": 0.2,
            "node_death_timeout_s": 1.5,
            "fence_kill_grace_s": 0.5,
            "log_to_driver": False,
        },
    )
    try:
        b = c.add_node(num_cpus=1, resources={"gadget": 1})
        target = b.node_id_hex

        @ray_tpu.remote(resources={"gadget": 1}, max_restarts=2)
        class Counter:
            def __init__(self):
                self.marker = uuid.uuid4().hex
                self.tokens = []

            def inc(self, token):
                self.tokens.append(token)
                return (self.marker, len(self.tokens))

            def log(self):
                return (self.marker, list(self.tokens))

        a = Counter.remote()
        from ray_tpu.core import runtime_context

        runtime = runtime_context.current_runtime()
        key = a.actor_id.binary()
        deadline = time.time() + 30
        warm = 0
        while time.time() < deadline:
            ray_tpu.get(a.inc.remote(f"warm-{warm}"), timeout=30)
            warm += 1
            st = runtime._direct_states.get(key)
            if st is not None and st["status"] == "ready":
                break
            time.sleep(0.05)
        else:
            raise AssertionError("direct channel never engaged")
        assert st["chan"].incarnation >= 1
        assert st["chan"].node_hex == target

        # The restart target joins BEFORE the partition so placement
        # is deterministic: the only other gadget node.
        c.add_node(num_cpus=1, resources={"gadget": 1})
        c.wait_for_nodes(3)

        # Sticky asymmetric partition: ONLY node B's heartbeat send is
        # cut (mode=once + sticky partition semantics — the cable
        # stays cut); B's peer and direct planes remain healthy.
        _arm([{"point": "heartbeat", "mode": "once",
               "action": "partition", "node": target}])

        results = []  # (marker, count) per SUCCESSFUL call, in order
        errors = []
        stop = threading.Event()

        def hammer():
            i = 0
            while not stop.is_set():
                refs = [a.inc.remote(f"t{i}-{j}") for j in range(4)]
                i += 1
                # Per-ref gets: every successful execution's result is
                # captured even when a sibling in the burst is refused.
                for r in refs:
                    try:
                        results.append(ray_tpu.get(r, timeout=30))
                    except Exception as e:  # noqa: BLE001 — recorded
                        errors.append(repr(e))
                time.sleep(0.02)

        t = threading.Thread(target=hammer, daemon=True)
        t.start()
        try:
            t_armed = time.time()
            deadline = time.time() + 30
            while time.time() < deadline:
                views = {v["NodeID"]: v for v in ray_tpu.nodes()}
                if views.get(target, {}).get("State") == "dead":
                    break
                time.sleep(0.1)
            else:
                raise AssertionError("node never declared dead")

            # Fence decision is an observable NODE event.
            assert _node_events("FENCE", timeout=15), "no FENCE event"

            # Results from the RESTARTED incarnation must flow.
            first_marker = results[0][0] if results else None
            deadline = time.time() + 60
            while time.time() < deadline:
                if results and results[-1][0] != first_marker:
                    break
                time.sleep(0.2)
            else:
                raise AssertionError(
                    f"no results from restarted incarnation "
                    f"(errors tail: {errors[-3:]})"
                )
            time.sleep(1.0)
        finally:
            stop.set()
            t.join(timeout=30)

        markers = [m for m, _ in results]
        assert first_marker is not None
        new_marker = next(m for m in markers if m != first_marker)
        switch = markers.index(new_marker)
        # ZERO stale results: once the new incarnation answers, the
        # fenced incarnation never produces another result.
        assert all(m == new_marker for m in markers[switch:]), markers

        # ZERO double-executions, proven from the actor's own log: no
        # token executed twice on the new incarnation, and no token
        # that already succeeded on the OLD incarnation re-executed on
        # the new one (refused, not replayed).
        marker2, log2 = ray_tpu.get(a.log.remote(), timeout=60)
        assert marker2 == new_marker
        assert len(log2) == len(set(log2)), "double execution"
        # Old-incarnation tokens never re-executed on the new one: the
        # new log only holds tokens the old log could not have (counts
        # are per-incarnation and strictly increasing per caller).
        old_counts = [n for m, n in results if m == first_marker]
        new_counts = [n for m, n in results if m == new_marker]
        assert old_counts == sorted(set(old_counts)), old_counts
        assert new_counts == sorted(set(new_counts)), new_counts

        # Fenced in-flight calls are refused OR re-routed exactly-once
        # onto the new incarnation — either way none is lost silently:
        # every submitted call either appears in `results` or raised.
        # (Refusals only occur when a call was unanswered at the exact
        # teardown instant, so an empty error list is a legal outcome.)
        for err in errors:
            assert ("ActorDied" in err or "fenced" in err
                    or "ConnectionError" in err or "Timeout" in err), err

        # Heal: disarm the plan. The zombie's reconnect re-registers;
        # the reply's fenced_at makes it self-terminate its workers and
        # rejoin as a FRESH node incarnation.
        _arm([])
        deadline = time.time() + 60
        row = None
        while time.time() < deadline:
            rows = {v["NodeID"]: v for v in ray_tpu.nodes()}
            row = rows.get(target)
            if (row and row.get("State") == "alive"
                    and int(row.get("Incarnation") or 1) >= 2):
                break
            time.sleep(0.2)
        else:
            raise AssertionError(f"zombie never rejoined fresh: {row}")
        assert _node_events("declared dead", timeout=20), \
            "no zombie self-termination event"

        # The restarted actor keeps serving after the heal.
        m3, _ = ray_tpu.get(a.inc.remote("post-heal"), timeout=60)
        assert m3 == new_marker
    finally:
        try:
            _arm([])
        except Exception:
            pass
        faults.clear()
        c.shutdown()
