"""ASGI ingress (ref: serve's FastAPI/ASGI integration via the uvicorn
proxy; here a dependency-free ASGI-3 bridge, serve/asgi_ingress.py)."""

import json
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve

# The app factory lives in this worker-unimportable test module; ship it
# by value.
import cloudpickle as _cloudpickle
import sys as _sys

_cloudpickle.register_pickle_by_value(_sys.modules[__name__])


def _app_factory():
    """A small hand-written ASGI-3 app (no framework in the image):
    routes exercise method, path, query string, request body, custom
    status + headers, and per-replica state."""
    state = {"hits": 0}

    async def app(scope, receive, send):
        assert scope["type"] == "http"
        path = scope["path"]
        state["hits"] += 1
        if path == "/hello":
            body = json.dumps({
                "msg": "hi",
                "q": scope["query_string"].decode(),
                "hits": state["hits"],
            }).encode()
            status, headers = 200, [(b"x-app", b"asgi-demo"),
                                    (b"content-type", b"application/json")]
        elif path == "/echo" and scope["method"] == "PUT":
            msg = await receive()
            body = msg["body"].upper()
            status, headers = 201, [(b"content-type",
                                     b"application/octet-stream")]
        else:
            body = b"nope"
            status, headers = 404, []
        await send({"type": "http.response.start", "status": status,
                    "headers": headers})
        await send({"type": "http.response.body", "body": body})

    return app


@pytest.fixture
def serve_rt(ray_tpu_start):
    yield
    serve.shutdown()


def test_asgi_ingress(serve_rt):
    handle = serve.run(serve.asgi(_app_factory, name="app"), name="app")
    port = handle.http_port

    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/app/hello?who=x", timeout=60) as r:
        assert r.status == 200
        assert r.headers["x-app"] == "asgi-demo"
        out = json.loads(r.read())
    assert out["msg"] == "hi" and out["q"] == "who=x"

    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/app/echo", data=b"shout",
        method="PUT")
    with urllib.request.urlopen(req, timeout=60) as r:
        assert r.status == 201
        assert r.read() == b"SHOUT"

    # unknown path relays the app's own 404 (not the proxy's)
    try:
        urllib.request.urlopen(
            f"http://127.0.0.1:{port}/app/missing", timeout=60)
        raise AssertionError("expected 404")
    except urllib.error.HTTPError as e:
        assert e.code == 404 and e.read() == b"nope"


def test_asgi_via_per_node_proxy(serve_rt):
    """Dynamically discovered routes carry the ASGI flag (controller
    routing snapshot), so per-node ProxyActors forward raw HTTP too."""
    from ray_tpu.serve import http_proxy

    serve.run(serve.asgi(_app_factory, name="napp"), name="napp")
    proxies = http_proxy.start_per_node_proxies(port=0)
    try:
        (_, port), = list(proxies.values())[:1]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/napp/hello?q=1",
                timeout=60) as r:
            assert r.status == 200
            assert json.loads(r.read())["msg"] == "hi"
    finally:
        for actor, _ in proxies.values():
            try:
                ray_tpu.get(actor.shutdown.remote(), timeout=10)
                ray_tpu.kill(actor)
            except Exception:
                pass


def test_asgi_root_query(serve_rt):
    """A bare route with a query string routes correctly (name parsing
    strips the query)."""
    handle = serve.run(serve.asgi(_app_factory, name="qapp"),
                       name="qapp")
    # /qapp?x=1 -> app path "/" -> app returns 404 ("nope"), which still
    # proves routing reached the app rather than a proxy-level 404.
    try:
        urllib.request.urlopen(
            f"http://127.0.0.1:{handle.http_port}/qapp?x=1", timeout=60)
        raise AssertionError("expected app-level 404")
    except urllib.error.HTTPError as e:
        assert e.code == 404 and e.read() == b"nope"
