"""Autoscaler tests (ref analogue: the fake_multi_node autoscaler tests)."""

import os
import time

import pytest

import ray_tpu
from ray_tpu.autoscaler import Autoscaler, AutoscalerConfig


def test_autoscaler_up_and_down():
    """Queued tasks beyond capacity add worker nodes; idleness removes
    them."""
    ray_tpu.init(num_cpus=1, system_config={"heartbeat_interval_s": 0.1})
    scaler = None
    try:
        scaler = Autoscaler(AutoscalerConfig(
            min_workers=0, max_workers=2,
            worker_resources={"CPU": 2},
            upscale_delay_s=0.3, idle_timeout_s=1.5, interval_s=0.2,
        )).start()

        @ray_tpu.remote(num_cpus=1)
        def busy(x):
            time.sleep(1.5)
            return x

        # 6 CPU-seconds of demand against a 1-CPU head.
        refs = [busy.remote(i) for i in range(6)]
        deadline = time.monotonic() + 30
        grew = 0
        while time.monotonic() < deadline:
            grew = max(grew, scaler.num_workers())
            if grew >= 1:
                break
            time.sleep(0.1)
        assert grew >= 1, "autoscaler never added a worker"
        assert sorted(ray_tpu.get(refs, timeout=60)) == list(range(6))
        # Idle: workers drain away.
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if scaler.num_workers() == 0:
                break
            time.sleep(0.2)
        assert scaler.num_workers() == 0, "idle workers not terminated"
    finally:
        if scaler is not None:
            scaler.shutdown()
        ray_tpu.shutdown()


def test_autoscaler_provisions_by_shape():
    """A pending {"TPU": 4} task must provision the TPU node type, not a
    CPU worker (ref analogue: resource_demand_scheduler node-type
    selection)."""
    ray_tpu.init(num_cpus=1, system_config={
        "heartbeat_interval_s": 0.1,
        "infeasible_grace_s": 60.0,
    })
    scaler = None
    try:
        scaler = Autoscaler(AutoscalerConfig(
            min_workers=0, max_workers=2,
            node_types={
                "cpu": {"resources": {"CPU": 2}},
                "tpu": {"resources": {"CPU": 1, "TPU": 4},
                        "labels": {"accel": "tpu-v5e"}},
            },
            upscale_delay_s=0.3, idle_timeout_s=30.0, interval_s=0.2,
        )).start()

        @ray_tpu.remote(resources={"TPU": 4})
        def use_tpu():
            return "ok"

        assert ray_tpu.get(use_tpu.remote(), timeout=90) == "ok"
        from ray_tpu.core.runtime_context import current_runtime

        workers = [v for v in current_runtime().nodes()
                   if not v.get("is_head") and v.get("state") == "alive"]
        types = [(v.get("labels") or {}).get("rtpu-node-type")
                 for v in workers]
        assert "tpu" in types, f"no TPU-typed node launched: {types}"
        assert "cpu" not in types, f"CPU node launched for TPU demand: {types}"
    finally:
        if scaler is not None:
            scaler.shutdown()
        ray_tpu.shutdown()


def test_autoscaler_respects_min_workers():
    ray_tpu.init(num_cpus=1, system_config={"heartbeat_interval_s": 0.1})
    scaler = None
    try:
        scaler = Autoscaler(AutoscalerConfig(
            min_workers=1, max_workers=2, idle_timeout_s=0.5,
            interval_s=0.2,
        )).start()
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if scaler.num_workers() >= 1:
                break
            time.sleep(0.1)
        assert scaler.num_workers() >= 1
        time.sleep(2.0)  # idle, but floor holds
        assert scaler.num_workers() >= 1
    finally:
        if scaler is not None:
            scaler.shutdown()
        ray_tpu.shutdown()


def test_cluster_yaml_validation(tmp_path):
    """Cluster YAML schema errors fail fast (ref: ray-schema.json)."""
    import pytest as _pytest

    from ray_tpu.autoscaler.cluster_config import load_cluster_config

    good = tmp_path / "good.yaml"
    good.write_text(
        "cluster_name: t\nmax_workers: 2\n"
        "provider: {type: local}\n"
        "available_node_types:\n  w:\n    resources: {CPU: 1}\n"
    )
    cfg = load_cluster_config(str(good))
    assert cfg["cluster_name"] == "t" and cfg["max_workers"] == 2

    bad = tmp_path / "bad.yaml"
    bad.write_text("cluster_name: t\nworkers_max: 2\n")
    with _pytest.raises(ValueError, match="unknown cluster config keys"):
        load_cluster_config(str(bad))

    bad2 = tmp_path / "bad2.yaml"
    bad2.write_text("provider: {type: gcp}\n")
    with _pytest.raises(ValueError, match="local|ssh"):
        load_cluster_config(str(bad2))

    bad3 = tmp_path / "bad3.yaml"
    bad3.write_text(
        "provider: {type: local}\n"
        "available_node_types:\n  w: {labels: {a: b}}\n"
    )
    with _pytest.raises(ValueError, match="resources"):
        load_cluster_config(str(bad3))


def test_ssh_provider_command_shape():
    """SSH provider builds a correct remote-launch argv (no reachable
    ssh hosts in the sandbox; the command is the contract)."""
    from ray_tpu.autoscaler.node_provider import SSHNodeProvider

    p = SSHNodeProvider("10.0.0.1:6380", worker_ips=["10.0.0.2"],
                        ssh_user="ubuntu", ssh_key="~/.ssh/k")
    cmd = p.ssh_command("10.0.0.2", "ssh-n1", {"CPU": 2.0},
                        {"pool": "x"})
    assert cmd[0] == "ssh" and "ubuntu@10.0.0.2" in cmd
    remote = cmd[-1]
    assert "RAY_TPU_GCS_ADDRESS=10.0.0.1:6380" in remote
    assert "RAY_TPU_SESSION_DIR=" in remote and "mkdir -p" in remote
    assert "ray_tpu.core.node_main" in remote
    assert '"CPU": 2.0' in remote


def test_rtpu_up_down_e2e(tmp_path):
    """`rtpu up <yaml>` starts a head whose autoscaler launches a
    provider worker for a demanded shape; `rtpu down` terminates
    everything (ref: `ray up` / `ray down` over commands.py)."""
    import subprocess
    import sys as _sys

    cfg = tmp_path / "cluster.yaml"
    cfg.write_text(
        "cluster_name: e2e\n"
        "max_workers: 1\n"
        "idle_timeout_s: 60\n"
        "upscale_delay_s: 0.2\n"
        "head:\n  num_cpus: 1\n  port: 0\n"
        "provider: {type: local}\n"
        "available_node_types:\n"
        "  gadget_worker:\n"
        "    resources: {CPU: 1, gadget: 1}\n"
    )
    env = dict(os.environ)
    up = subprocess.run(
        [_sys.executable, "-m", "ray_tpu.scripts.cli", "up", str(cfg)],
        capture_output=True, text=True, timeout=120, env=env,
    )
    assert up.returncode == 0, up.stdout + up.stderr
    try:
        address = None
        for line in up.stdout.splitlines():
            if "address=" in line:
                address = line.split("address=")[1].strip("')")
        assert address, up.stdout

        driver = (
            "import ray_tpu\n"
            f"ray_tpu.init(address={address!r}, "
            "system_config={'infeasible_grace_s': 90})\n"
            "@ray_tpu.remote(resources={'gadget': 1})\n"
            "def probe():\n"
            "    return 'scaled'\n"
            "print(ray_tpu.get(probe.remote(), timeout=90))\n"
            "ray_tpu.shutdown()\n"
        )
        out = subprocess.run(
            [_sys.executable, "-c", driver], capture_output=True,
            text=True, timeout=180, env=env,
        )
        assert out.returncode == 0, out.stdout + out.stderr
        assert "scaled" in out.stdout
    finally:
        subprocess.run(
            [_sys.executable, "-m", "ray_tpu.scripts.cli", "down",
             str(cfg)],
            capture_output=True, text=True, timeout=60, env=env,
        )
