"""Autoscaler tests (ref analogue: the fake_multi_node autoscaler tests)."""

import time

import pytest

import ray_tpu
from ray_tpu.autoscaler import Autoscaler, AutoscalerConfig


def test_autoscaler_up_and_down():
    """Queued tasks beyond capacity add worker nodes; idleness removes
    them."""
    ray_tpu.init(num_cpus=1, system_config={"heartbeat_interval_s": 0.1})
    scaler = None
    try:
        scaler = Autoscaler(AutoscalerConfig(
            min_workers=0, max_workers=2,
            worker_resources={"CPU": 2},
            upscale_delay_s=0.3, idle_timeout_s=1.5, interval_s=0.2,
        )).start()

        @ray_tpu.remote(num_cpus=1)
        def busy(x):
            time.sleep(1.5)
            return x

        # 6 CPU-seconds of demand against a 1-CPU head.
        refs = [busy.remote(i) for i in range(6)]
        deadline = time.monotonic() + 30
        grew = 0
        while time.monotonic() < deadline:
            grew = max(grew, scaler.num_workers())
            if grew >= 1:
                break
            time.sleep(0.1)
        assert grew >= 1, "autoscaler never added a worker"
        assert sorted(ray_tpu.get(refs, timeout=60)) == list(range(6))
        # Idle: workers drain away.
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if scaler.num_workers() == 0:
                break
            time.sleep(0.2)
        assert scaler.num_workers() == 0, "idle workers not terminated"
    finally:
        if scaler is not None:
            scaler.shutdown()
        ray_tpu.shutdown()


def test_autoscaler_provisions_by_shape():
    """A pending {"TPU": 4} task must provision the TPU node type, not a
    CPU worker (ref analogue: resource_demand_scheduler node-type
    selection)."""
    ray_tpu.init(num_cpus=1, system_config={
        "heartbeat_interval_s": 0.1,
        "infeasible_grace_s": 60.0,
    })
    scaler = None
    try:
        scaler = Autoscaler(AutoscalerConfig(
            min_workers=0, max_workers=2,
            node_types={
                "cpu": {"resources": {"CPU": 2}},
                "tpu": {"resources": {"CPU": 1, "TPU": 4},
                        "labels": {"accel": "tpu-v5e"}},
            },
            upscale_delay_s=0.3, idle_timeout_s=30.0, interval_s=0.2,
        )).start()

        @ray_tpu.remote(resources={"TPU": 4})
        def use_tpu():
            return "ok"

        assert ray_tpu.get(use_tpu.remote(), timeout=90) == "ok"
        from ray_tpu.core.runtime_context import current_runtime

        workers = [v for v in current_runtime().nodes()
                   if not v.get("is_head") and v.get("state") == "alive"]
        types = [(v.get("labels") or {}).get("rtpu-node-type")
                 for v in workers]
        assert "tpu" in types, f"no TPU-typed node launched: {types}"
        assert "cpu" not in types, f"CPU node launched for TPU demand: {types}"
    finally:
        if scaler is not None:
            scaler.shutdown()
        ray_tpu.shutdown()


def test_autoscaler_respects_min_workers():
    ray_tpu.init(num_cpus=1, system_config={"heartbeat_interval_s": 0.1})
    scaler = None
    try:
        scaler = Autoscaler(AutoscalerConfig(
            min_workers=1, max_workers=2, idle_timeout_s=0.5,
            interval_s=0.2,
        )).start()
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if scaler.num_workers() >= 1:
                break
            time.sleep(0.1)
        assert scaler.num_workers() >= 1
        time.sleep(2.0)  # idle, but floor holds
        assert scaler.num_workers() >= 1
    finally:
        if scaler is not None:
            scaler.shutdown()
        ray_tpu.shutdown()
