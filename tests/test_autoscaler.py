"""Autoscaler tests (ref analogue: the fake_multi_node autoscaler tests)."""

import os
import time

import pytest

import ray_tpu
from ray_tpu.autoscaler import Autoscaler, AutoscalerConfig


def test_autoscaler_up_and_down():
    """Queued tasks beyond capacity add worker nodes; idleness removes
    them."""
    ray_tpu.init(num_cpus=1, system_config={"heartbeat_interval_s": 0.1})
    scaler = None
    try:
        scaler = Autoscaler(AutoscalerConfig(
            min_workers=0, max_workers=2,
            worker_resources={"CPU": 2},
            upscale_delay_s=0.3, idle_timeout_s=1.5, interval_s=0.2,
        )).start()

        @ray_tpu.remote(num_cpus=1)
        def busy(x):
            time.sleep(1.5)
            return x

        # 6 CPU-seconds of demand against a 1-CPU head.
        refs = [busy.remote(i) for i in range(6)]
        deadline = time.monotonic() + 30
        grew = 0
        while time.monotonic() < deadline:
            grew = max(grew, scaler.num_workers())
            if grew >= 1:
                break
            time.sleep(0.1)
        assert grew >= 1, "autoscaler never added a worker"
        assert sorted(ray_tpu.get(refs, timeout=60)) == list(range(6))
        # Idle: workers drain away.
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if scaler.num_workers() == 0:
                break
            time.sleep(0.2)
        assert scaler.num_workers() == 0, "idle workers not terminated"
    finally:
        if scaler is not None:
            scaler.shutdown()
        ray_tpu.shutdown()


def test_autoscaler_provisions_by_shape():
    """A pending {"TPU": 4} task must provision the TPU node type, not a
    CPU worker (ref analogue: resource_demand_scheduler node-type
    selection)."""
    ray_tpu.init(num_cpus=1, system_config={
        "heartbeat_interval_s": 0.1,
        "infeasible_grace_s": 60.0,
    })
    scaler = None
    try:
        scaler = Autoscaler(AutoscalerConfig(
            min_workers=0, max_workers=2,
            node_types={
                "cpu": {"resources": {"CPU": 2}},
                "tpu": {"resources": {"CPU": 1, "TPU": 4},
                        "labels": {"accel": "tpu-v5e"}},
            },
            upscale_delay_s=0.3, idle_timeout_s=30.0, interval_s=0.2,
        )).start()

        @ray_tpu.remote(resources={"TPU": 4})
        def use_tpu():
            return "ok"

        assert ray_tpu.get(use_tpu.remote(), timeout=90) == "ok"
        from ray_tpu.core.runtime_context import current_runtime

        workers = [v for v in current_runtime().nodes()
                   if not v.get("is_head") and v.get("state") == "alive"]
        types = [(v.get("labels") or {}).get("rtpu-node-type")
                 for v in workers]
        assert "tpu" in types, f"no TPU-typed node launched: {types}"
        assert "cpu" not in types, f"CPU node launched for TPU demand: {types}"
    finally:
        if scaler is not None:
            scaler.shutdown()
        ray_tpu.shutdown()


def test_autoscaler_respects_min_workers():
    ray_tpu.init(num_cpus=1, system_config={"heartbeat_interval_s": 0.1})
    scaler = None
    try:
        scaler = Autoscaler(AutoscalerConfig(
            min_workers=1, max_workers=2, idle_timeout_s=0.5,
            interval_s=0.2,
        )).start()
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if scaler.num_workers() >= 1:
                break
            time.sleep(0.1)
        assert scaler.num_workers() >= 1
        time.sleep(2.0)  # idle, but floor holds
        assert scaler.num_workers() >= 1
    finally:
        if scaler is not None:
            scaler.shutdown()
        ray_tpu.shutdown()


def test_cluster_yaml_validation(tmp_path):
    """Cluster YAML schema errors fail fast (ref: ray-schema.json)."""
    import pytest as _pytest

    from ray_tpu.autoscaler.cluster_config import load_cluster_config

    good = tmp_path / "good.yaml"
    good.write_text(
        "cluster_name: t\nmax_workers: 2\n"
        "provider: {type: local}\n"
        "available_node_types:\n  w:\n    resources: {CPU: 1}\n"
    )
    cfg = load_cluster_config(str(good))
    assert cfg["cluster_name"] == "t" and cfg["max_workers"] == 2

    bad = tmp_path / "bad.yaml"
    bad.write_text("cluster_name: t\nworkers_max: 2\n")
    with _pytest.raises(ValueError, match="unknown cluster config keys"):
        load_cluster_config(str(bad))

    bad2 = tmp_path / "bad2.yaml"
    bad2.write_text("provider: {type: gcp}\n")
    with _pytest.raises(ValueError, match="local|ssh"):
        load_cluster_config(str(bad2))

    bad3 = tmp_path / "bad3.yaml"
    bad3.write_text(
        "provider: {type: local}\n"
        "available_node_types:\n  w: {labels: {a: b}}\n"
    )
    with _pytest.raises(ValueError, match="resources"):
        load_cluster_config(str(bad3))


def test_ssh_provider_command_shape():
    """SSH provider builds a correct remote-launch argv (no reachable
    ssh hosts in the sandbox; the command is the contract)."""
    from ray_tpu.autoscaler.node_provider import SSHNodeProvider

    p = SSHNodeProvider("10.0.0.1:6380", worker_ips=["10.0.0.2"],
                        ssh_user="ubuntu", ssh_key="~/.ssh/k")
    cmd = p.ssh_command("10.0.0.2", "ssh-n1", {"CPU": 2.0},
                        {"pool": "x"})
    assert cmd[0] == "ssh" and "ubuntu@10.0.0.2" in cmd
    remote = cmd[-1]
    assert "RAY_TPU_GCS_ADDRESS=10.0.0.1:6380" in remote
    assert "RAY_TPU_SESSION_DIR=" in remote and "mkdir -p" in remote
    assert "ray_tpu.core.node_main" in remote
    assert '"CPU": 2.0' in remote


def test_rtpu_up_down_e2e(tmp_path):
    """`rtpu up <yaml>` starts a head whose autoscaler launches a
    provider worker for a demanded shape; `rtpu down` terminates
    everything (ref: `ray up` / `ray down` over commands.py)."""
    import subprocess
    import sys as _sys

    cfg = tmp_path / "cluster.yaml"
    cfg.write_text(
        "cluster_name: e2e\n"
        "max_workers: 1\n"
        "idle_timeout_s: 60\n"
        "upscale_delay_s: 0.2\n"
        "head:\n  num_cpus: 1\n  port: 0\n"
        "provider: {type: local}\n"
        "available_node_types:\n"
        "  gadget_worker:\n"
        "    resources: {CPU: 1, gadget: 1}\n"
    )
    env = dict(os.environ)
    up = subprocess.run(
        [_sys.executable, "-m", "ray_tpu.scripts.cli", "up", str(cfg)],
        capture_output=True, text=True, timeout=120, env=env,
    )
    assert up.returncode == 0, up.stdout + up.stderr
    try:
        address = None
        for line in up.stdout.splitlines():
            if "address=" in line:
                address = line.split("address=")[1].strip("')")
        assert address, up.stdout

        driver = (
            "import ray_tpu\n"
            f"ray_tpu.init(address={address!r}, "
            "system_config={'infeasible_grace_s': 90})\n"
            "@ray_tpu.remote(resources={'gadget': 1})\n"
            "def probe():\n"
            "    return 'scaled'\n"
            "print(ray_tpu.get(probe.remote(), timeout=90))\n"
            "ray_tpu.shutdown()\n"
        )
        out = subprocess.run(
            [_sys.executable, "-c", driver], capture_output=True,
            text=True, timeout=180, env=env,
        )
        assert out.returncode == 0, out.stdout + out.stderr
        assert "scaled" in out.stdout
    finally:
        subprocess.run(
            [_sys.executable, "-m", "ray_tpu.scripts.cli", "down",
             str(cfg)],
            capture_output=True, text=True, timeout=60, env=env,
        )


# ---------------------------------------------------------- gcp_tpu provider

class _FakeTpuHttp:
    """Records TPU REST calls and keeps a node table (the injectable
    HTTP layer of GCPTpuNodeProvider)."""

    def __init__(self):
        self.calls = []
        self.nodes = {}

    def request(self, method, url, body=None):
        self.calls.append((method, url, body))
        if method == "POST":
            node_id = url.rsplit("nodeId=", 1)[-1]
            self.nodes[node_id] = {
                "name": url.split("?")[0] + "/" + node_id,
                "state": "READY",
                "labels": dict(body.get("labels") or {}),
                "acceleratorType": body.get("acceleratorType"),
                "metadata": body.get("metadata") or {},
            }
            return {"name": f"operations/op-{node_id}"}
        if method == "DELETE":
            node_id = url.rsplit("/", 1)[-1]
            self.nodes.pop(node_id, None)
            return {}
        if method == "GET":
            return {"nodes": list(self.nodes.values())}
        raise AssertionError(method)


def test_gcp_tpu_provider_rest_shape():
    """create/list/terminate against the (fake) TPU REST API: one
    provider node = one slice; the startup script joins every host to
    the cluster with the shared provider-node id in its labels."""
    from ray_tpu.autoscaler.node_provider import GCPTpuNodeProvider

    http = _FakeTpuHttp()
    p = GCPTpuNodeProvider(
        "10.0.0.1:6380", project="proj", zone="us-central2-b",
        cluster_name="demo", http=http,
    )
    p.node_type_configs = {
        "tpu_v5e_16": {
            "resources": {"TPU": 4, "CPU": 8},
            "hosts_per_node": 4,
            "accelerator_type": "v5litepod-16",
            "runtime_version": "v2-alpha-tpuv5-lite",
        }
    }
    nid = p.create_node({"TPU": 4, "CPU": 8},
                        labels={"rtpu-node-type": "tpu_v5e_16"})
    method, url, body = http.calls[0]
    assert method == "POST" and "projects/proj/locations/us-central2-b" in url
    assert body["acceleratorType"] == "v5litepod-16"
    assert body["runtimeVersion"] == "v2-alpha-tpuv5-lite"
    script = body["metadata"]["startup-script"]
    assert "RAY_TPU_GCS_ADDRESS=10.0.0.1:6380" in script
    assert "ray_tpu.core.node_main" in script
    assert nid in script  # session dir + provider id propagate
    assert '"rtpu-provider-node-id": "%s"' % nid in __import__(
        "json"
    ).dumps(body["labels"])  # API labels carry the id for list()

    assert p.non_terminated_nodes() == [nid]
    p.terminate_node(nid)
    assert ("DELETE", f"{p._parent()}/nodes/{nid}", None) in http.calls
    assert p.non_terminated_nodes() == []


def test_gcp_tpu_slice_scaling():
    """Slice-aware autoscaling: 4 pending per-host {"TPU": 4} shapes
    launch ONE v5e-16 slice (4 hosts), not four; the slice only drains
    when EVERY host is idle."""
    from ray_tpu.autoscaler.node_provider import (
        GCPTpuNodeProvider,
        PROVIDER_NODE_LABEL,
    )

    http = _FakeTpuHttp()
    p = GCPTpuNodeProvider(
        "10.0.0.1:6380", project="proj", zone="z", http=http,
    )
    tcfg = {
        "resources": {"TPU": 4, "CPU": 8},
        "hosts_per_node": 4,
        "accelerator_type": "v5litepod-16",
    }
    p.node_type_configs = {"tpu_v5e_16": tcfg}

    views = [{
        "state": "alive", "labels": {},
        "pending_shapes": [({"TPU": 4}, 4)],
        "resources_available": {"CPU": 1},
        "resources_total": {"CPU": 1},
        "pending_tasks": 4,
    }]
    scaler = Autoscaler(
        AutoscalerConfig(
            min_workers=0, max_workers=2,
            node_types={"tpu_v5e_16": tcfg},
            upscale_delay_s=0.0, idle_timeout_s=0.2, interval_s=10,
        ),
        p, nodes_fn=lambda: views,
    )
    scaler._reconcile_once()
    scaler._reconcile_once()
    creates = [c for c in http.calls if c[0] == "POST"]
    assert len(creates) == 1, f"expected ONE slice launch, got {creates}"
    (nid,) = p.non_terminated_nodes()

    # STAGGERED boot: only host 0 registers, demand still pending. The
    # missing hosts' phantom capacity must keep covering the remaining
    # shapes (no duplicate slice), and the partially-registered slice
    # must NOT be judged idle (no premature teardown mid-boot).
    views.append({
        "state": "alive",
        "labels": {PROVIDER_NODE_LABEL: nid},
        "pending_tasks": 0,
        "resources_available": {"TPU": 4, "CPU": 8},
        "resources_total": {"TPU": 4, "CPU": 8},
    })
    scaler._reconcile_once()
    time.sleep(0.3)
    scaler._reconcile_once()
    assert sum(1 for c in http.calls if c[0] == "POST") == 1, (
        "staggered host registration caused a duplicate slice launch"
    )
    assert p.non_terminated_nodes() == [nid], (
        "partially-registered slice was torn down mid-boot"
    )
    views.pop()

    # All 4 hosts register; demand satisfied; 3 idle + 1 busy => NOT idle.
    views[0]["pending_shapes"] = []
    views[0]["pending_tasks"] = 0
    host_views = [
        {
            "state": "alive",
            "labels": {PROVIDER_NODE_LABEL: nid},
            "pending_tasks": 0,
            "resources_available": {"TPU": 4, "CPU": 8},
            "resources_total": {"TPU": 4, "CPU": 8},
        }
        for _ in range(4)
    ]
    host_views[3]["resources_available"] = {"TPU": 0, "CPU": 8}
    views.extend(host_views)
    scaler._reconcile_once()
    time.sleep(0.3)
    scaler._reconcile_once()
    assert p.non_terminated_nodes() == [nid], "busy slice was drained"

    # Last host finishes: slice idles out as a UNIT.
    host_views[3]["resources_available"] = {"TPU": 4, "CPU": 8}
    scaler._reconcile_once()
    time.sleep(0.3)
    scaler._reconcile_once()
    assert p.non_terminated_nodes() == [], "idle slice not terminated"


def test_rtpu_up_gcp_tpu_fake_api(tmp_path):
    """`rtpu up` with a tpu-v5e-pod YAML against a FAKE TPU REST API:
    demanded {"TPU": 4} shapes make the head's autoscaler create a
    slice through the API; `rtpu down` deletes it."""
    import http.server
    import json as _json
    import subprocess
    import sys as _sys
    import threading

    state = {"nodes": {}, "creates": 0, "deletes": 0}

    class Handler(http.server.BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def _send(self, payload):
            body = _json.dumps(payload).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_POST(self):
            n = int(self.headers.get("Content-Length") or 0)
            body = _json.loads(self.rfile.read(n) or b"{}")
            node_id = self.path.rsplit("nodeId=", 1)[-1]
            state["nodes"][node_id] = {
                "name": node_id, "state": "READY",
                "labels": dict(body.get("labels") or {}),
            }
            state["creates"] += 1
            self._send({"name": f"operations/{node_id}"})

        def do_DELETE(self):
            node_id = self.path.rsplit("/", 1)[-1]
            state["nodes"].pop(node_id, None)
            state["deletes"] += 1
            self._send({})

        def do_GET(self):
            self._send({"nodes": list(state["nodes"].values())})

    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    api = f"http://127.0.0.1:{srv.server_address[1]}/v2"

    cfg = tmp_path / "tpu-pod.yaml"
    cfg.write_text(
        "cluster_name: tpupod\n"
        "max_workers: 2\n"
        "upscale_delay_s: 0.2\n"
        "boot_timeout_s: 600\n"
        "head:\n  num_cpus: 1\n  port: 0\n"
        "provider:\n"
        "  type: gcp_tpu\n"
        "  project: fake-proj\n"
        "  zone: us-central2-b\n"
        f"  api_base: {api}\n"
        "available_node_types:\n"
        "  tpu_v5e_16:\n"
        "    resources: {TPU: 4, CPU: 8}\n"
        "    hosts_per_node: 4\n"
        "    accelerator_type: v5litepod-16\n"
        "    runtime_version: v2-alpha-tpuv5-lite\n"
    )
    env = dict(os.environ)
    up = subprocess.run(
        [_sys.executable, "-m", "ray_tpu.scripts.cli", "up", str(cfg)],
        capture_output=True, text=True, timeout=120, env=env,
    )
    assert up.returncode == 0, up.stdout + up.stderr
    try:
        address = None
        for line in up.stdout.splitlines():
            if "address=" in line:
                address = line.split("address=")[1].strip("')")
        assert address, up.stdout
        # Fire-and-forget demand: the shape can only run on a slice, so
        # it stays pending and the autoscaler must create one via the
        # fake API (no real VM ever joins; we assert the API call).
        driver = (
            "import ray_tpu, time\n"
            f"ray_tpu.init(address={address!r}, "
            "system_config={'infeasible_grace_s': 300})\n"
            "@ray_tpu.remote(resources={'TPU': 4})\n"
            "def probe():\n    return 'ok'\n"
            "probe.remote()\n"
            "time.sleep(25)\n"
        )
        proc = subprocess.Popen(
            [_sys.executable, "-c", driver],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL, env=env,
        )
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline and state["creates"] == 0:
            time.sleep(0.3)
        proc.terminate()
        assert state["creates"] >= 1, "autoscaler never created a slice"
        (nid,) = list(state["nodes"])
        assert nid.startswith("tpu-tpupod-")
    finally:
        subprocess.run(
            [_sys.executable, "-m", "ray_tpu.scripts.cli", "down",
             str(cfg)],
            capture_output=True, text=True, timeout=60, env=env,
        )
        # `down` SIGTERMs the head; its autoscaler deletes the slice on
        # the way out — asynchronously. Wait for the DELETE to land.
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and state["deletes"] == 0:
            time.sleep(0.3)
        srv.shutdown()
    assert state["deletes"] >= 1, "rtpu down did not delete the slice"
    assert not state["nodes"]
