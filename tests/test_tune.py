"""Tune tests (ref analogue: python/ray/tune/tests/)."""

import numpy as np
import pytest

from ray_tpu import tune
from ray_tpu.tune import ASHAScheduler, TuneConfig, Tuner
from ray_tpu.tune.search_space import generate_variants
from ray_tpu.train.config import RunConfig


def test_generate_variants_grid_and_random():
    space = {
        "lr": tune.loguniform(1e-4, 1e-1),
        "bs": tune.grid_search([16, 32]),
        "opt": "adam",
    }
    variants = generate_variants(space, num_samples=3, seed=0)
    assert len(variants) == 6  # 2 grid x 3 samples
    assert {v["bs"] for v in variants} == {16, 32}
    assert all(1e-4 <= v["lr"] <= 1e-1 for v in variants)
    assert all(v["opt"] == "adam" for v in variants)


def test_choice_and_randint_bounds():
    space = {"c": tune.choice(["a", "b"]), "n": tune.randint(1, 5)}
    vs = generate_variants(space, num_samples=20, seed=1)
    assert {v["c"] for v in vs} <= {"a", "b"}
    assert all(1 <= v["n"] < 5 for v in vs)


def test_tuner_basic(ray_tpu_start, tmp_path):
    def trainable(config):
        score = -(config["x"] - 3.0) ** 2
        tune.report({"score": score})

    grid = Tuner(
        trainable,
        param_space={"x": tune.grid_search([0.0, 1.0, 3.0, 5.0])},
        tune_config=TuneConfig(metric="score", mode="max"),
        run_config=RunConfig(storage_path=str(tmp_path / "tune1")),
    ).fit()
    assert len(grid) == 4
    best = grid.get_best_result()
    assert best.config["x"] == 3.0


def test_tuner_trial_error_isolated(ray_tpu_start, tmp_path):
    def trainable(config):
        if config["x"] == 1:
            raise RuntimeError("bad trial")
        tune.report({"score": config["x"]})

    grid = Tuner(
        trainable,
        param_space={"x": tune.grid_search([0, 1, 2])},
        tune_config=TuneConfig(metric="score", mode="max"),
        run_config=RunConfig(storage_path=str(tmp_path / "tune2")),
    ).fit()
    assert len(grid.errors) == 1
    assert grid.get_best_result().config["x"] == 2


def test_asha_early_stops_bad_trials(ray_tpu_start, tmp_path):
    def trainable(config):
        import time

        for i in range(1, 21):
            # Strong configs also iterate faster, so their rung entries land
            # first and weak trials face a real threshold (async ASHA only
            # culls against results already recorded at the rung).
            score = config["slope"] * i
            tune.report({"score": score, "training_iteration": i})
            time.sleep(0.04 if config["slope"] >= 1 else 0.25)

    sched = ASHAScheduler(metric="score", mode="max", max_t=20,
                          grace_period=2, reduction_factor=2)
    grid = Tuner(
        trainable,
        param_space={"slope": tune.grid_search([0.1, 0.2, 1.0, 2.0])},
        tune_config=TuneConfig(metric="score", mode="max", scheduler=sched,
                               max_concurrent_trials=4),
        run_config=RunConfig(storage_path=str(tmp_path / "tune3")),
    ).fit()
    best = grid.get_best_result()
    assert best.config["slope"] == 2.0
    stopped = [r for r in grid if r.early_stopped]
    assert len(stopped) >= 1  # weak trials got culled
    # The strongest trial is never the one culled.
    assert all(r.config["slope"] != 2.0 for r in stopped)
