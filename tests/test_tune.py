"""Tune tests (ref analogue: python/ray/tune/tests/)."""

import numpy as np
import pytest

from ray_tpu import tune
from ray_tpu.tune import ASHAScheduler, TuneConfig, Tuner
from ray_tpu.tune.search_space import generate_variants
from ray_tpu.train.config import RunConfig


def test_generate_variants_grid_and_random():
    space = {
        "lr": tune.loguniform(1e-4, 1e-1),
        "bs": tune.grid_search([16, 32]),
        "opt": "adam",
    }
    variants = generate_variants(space, num_samples=3, seed=0)
    assert len(variants) == 6  # 2 grid x 3 samples
    assert {v["bs"] for v in variants} == {16, 32}
    assert all(1e-4 <= v["lr"] <= 1e-1 for v in variants)
    assert all(v["opt"] == "adam" for v in variants)


def test_choice_and_randint_bounds():
    space = {"c": tune.choice(["a", "b"]), "n": tune.randint(1, 5)}
    vs = generate_variants(space, num_samples=20, seed=1)
    assert {v["c"] for v in vs} <= {"a", "b"}
    assert all(1 <= v["n"] < 5 for v in vs)


def test_tuner_basic(ray_tpu_start, tmp_path):
    def trainable(config):
        score = -(config["x"] - 3.0) ** 2
        tune.report({"score": score})

    grid = Tuner(
        trainable,
        param_space={"x": tune.grid_search([0.0, 1.0, 3.0, 5.0])},
        tune_config=TuneConfig(metric="score", mode="max"),
        run_config=RunConfig(storage_path=str(tmp_path / "tune1")),
    ).fit()
    assert len(grid) == 4
    best = grid.get_best_result()
    assert best.config["x"] == 3.0


def test_tuner_trial_error_isolated(ray_tpu_start, tmp_path):
    def trainable(config):
        if config["x"] == 1:
            raise RuntimeError("bad trial")
        tune.report({"score": config["x"]})

    grid = Tuner(
        trainable,
        param_space={"x": tune.grid_search([0, 1, 2])},
        tune_config=TuneConfig(metric="score", mode="max"),
        run_config=RunConfig(storage_path=str(tmp_path / "tune2")),
    ).fit()
    assert len(grid.errors) == 1
    assert grid.get_best_result().config["x"] == 2


@pytest.mark.slow
def test_asha_early_stops_bad_trials(ray_tpu_start, tmp_path):
    def trainable(config):
        import time

        for i in range(1, 21):
            # Strong configs also iterate faster, so their rung entries land
            # first and weak trials face a real threshold (async ASHA only
            # culls against results already recorded at the rung).
            score = config["slope"] * i
            tune.report({"score": score, "training_iteration": i})
            time.sleep(0.04 if config["slope"] >= 1 else 0.25)

    sched = ASHAScheduler(metric="score", mode="max", max_t=20,
                          grace_period=2, reduction_factor=2)
    grid = Tuner(
        trainable,
        param_space={"slope": tune.grid_search([0.1, 0.2, 1.0, 2.0])},
        tune_config=TuneConfig(metric="score", mode="max", scheduler=sched,
                               max_concurrent_trials=4),
        run_config=RunConfig(storage_path=str(tmp_path / "tune3")),
    ).fit()
    best = grid.get_best_result()
    assert best.config["slope"] == 2.0
    stopped = [r for r in grid if r.early_stopped]
    assert len(stopped) >= 1  # weak trials got culled
    # The strongest trial is never the one culled.
    assert all(r.config["slope"] != 2.0 for r in stopped)


def test_hyperband_bracket_culling_unit():
    """Deterministic bracket behavior: within a bracket, a trial reaching
    a rung below the top-1/rf threshold is stopped."""
    from ray_tpu.tune.schedulers import CONTINUE, STOP

    hb = tune.HyperBandScheduler(
        metric="acc", mode="max", max_t=9, reduction_factor=3
    )
    # Round-robin assignment: a→bracket0, b→bracket1, c→bracket2,
    # d→bracket0 (same bracket as a).
    for tid in ("a", "b", "c", "d"):
        hb.on_trial_start(tid, {})
    # Bracket 0 rungs are [1, 3]. "a" reports first at rung 1 with a high
    # score and survives; "d" arrives later with a low score and is culled.
    assert hb.on_result("a", {"training_iteration": 1, "acc": 9.0}) \
        == CONTINUE
    assert hb.on_result("d", {"training_iteration": 1, "acc": 0.1}) == STOP
    # "a" keeps surviving its next rung.
    assert hb.on_result("a", {"training_iteration": 3, "acc": 27.0}) \
        == CONTINUE
    # Bracket 2 (largest starting budget) has no intermediate rungs:
    # "c" is never culled regardless of score.
    for t in range(1, 10):
        assert hb.on_result("c", {"training_iteration": t, "acc": 0.0}) \
            == CONTINUE


@pytest.mark.slow
def test_hyperband_integration(ray_tpu_start, tmp_path):
    """End-to-end HyperBand run: the best config wins."""
    def trainable(config):
        for i in range(9):
            tune.report({"acc": config["q"] * (i + 1)})

    res = Tuner(
        trainable,
        param_space={"q": tune.grid_search([0.1, 0.2, 0.5, 1.0])},
        tune_config=TuneConfig(
            metric="acc", mode="max",
            scheduler=tune.HyperBandScheduler(
                metric="acc", mode="max", max_t=9, reduction_factor=3
            ),
            max_concurrent_trials=4,
        ),
        run_config=RunConfig(storage_path=str(tmp_path)),
    ).fit()
    best = res.get_best_result()
    assert best.config["q"] == 1.0


@pytest.mark.slow
def test_pbt_exploits_and_mutates(ray_tpu_start, tmp_path):
    """PBT: bottom-quantile trials restart from a top trial's checkpoint
    with mutated hyperparameters and end up beating their original
    config (ref: pbt.py exploit/explore)."""
    import json

    from ray_tpu.train.checkpoint import Checkpoint

    def trainable(config):
        # State = accumulated score; good lr grows it fast, bad lr barely.
        session_ckpt = tune.get_checkpoint()
        total = 0.0
        start = 0
        if session_ckpt is not None:
            with open(session_ckpt.path + "/state.json") as f:
                st = json.load(f)
            total, start = st["total"], st["step"]
        import os

        import time as _time

        for step in range(start, 16):
            total += config["lr"]
            d = os.path.join(
                tmp_path, f"ckpt-{os.getpid()}-{step}"
            )
            os.makedirs(d, exist_ok=True)
            with open(d + "/state.json", "w") as f:
                json.dump({"total": total, "step": step + 1}, f)
            tune.report({"score": total}, checkpoint=Checkpoint(d))
            # Pace reports so the population's scores interleave at the
            # controller (PBT compares trials mid-flight).
            _time.sleep(0.1)

    pbt = tune.PopulationBasedTraining(
        metric="score", mode="max",
        perturbation_interval=4,
        hyperparam_mutations={"lr": tune.uniform(0.5, 1.0)},
        quantile_fraction=0.25,
        seed=0,
    )
    res = Tuner(
        trainable,
        param_space={"lr": tune.grid_search([0.01, 0.02, 0.9, 1.0])},
        tune_config=TuneConfig(metric="score", mode="max", scheduler=pbt,
                               max_concurrent_trials=4),
        run_config=RunConfig(storage_path=str(tmp_path)),
    ).fit()
    # The bottom trials must have been mutated away from their original lr.
    mutated = [r for r in res if r.config["lr"] not in
               (0.01, 0.02, 0.9, 1.0)]
    assert mutated, "no trial was exploited/mutated"
    # And every final score reflects mostly-good-lr training.
    best = res.get_best_result()
    assert best.metrics["score"] > 10.0


@pytest.mark.slow
def test_tuner_restore_resumes_incomplete(ray_tpu_start, tmp_path):
    """Tuner.restore: completed trials keep results; interrupted ones
    re-run from their last checkpoint (ref: Tuner.restore)."""
    import json
    import os

    from ray_tpu.train.checkpoint import Checkpoint
    from ray_tpu.tune.tuner import _Trial, Tuner as T

    marker = tmp_path / "progress.json"

    def trainable(config):
        ck = tune.get_checkpoint()
        start = 0
        if ck is not None:
            with open(os.path.join(ck.path, "s.json")) as f:
                start = json.load(f)["step"]
        for step in range(start, 4):
            d = str(tmp_path / f"rck-{config['tag']}-{step}")
            os.makedirs(d, exist_ok=True)
            with open(os.path.join(d, "s.json"), "w") as f:
                json.dump({"step": step + 1}, f)
            tune.report({"step_done": step, "start": start},
                        checkpoint=Checkpoint(d))

    storage = str(tmp_path / "exp")
    tuner = Tuner(
        trainable,
        param_space={"tag": tune.grid_search(["a", "b"])},
        tune_config=TuneConfig(metric="step_done", mode="max"),
        run_config=RunConfig(storage_path=storage),
    )
    res = tuner.fit()
    assert all(r.metrics["step_done"] == 3 for r in res)

    # Simulate an interruption: mark trial "a" as still running with a
    # checkpoint at step 2.
    state_path = os.path.join(storage, "experiment_state.json")
    with open(state_path) as f:
        state = json.load(f)
    import cloudpickle

    for row in state["trials"]:
        cfg = cloudpickle.loads(bytes.fromhex(row["config_pickle_hex"]))
        if cfg["tag"] == "a":
            row["state"] = "running"
            row["last_checkpoint"] = str(tmp_path / "rck-a-1")
            row["history"] = row["history"][:2]
    with open(state_path, "w") as f:
        json.dump(state, f)

    restored = Tuner.restore(storage, trainable)
    res2 = restored.fit()
    by_tag = {r.config["tag"]: r for r in res2}
    # "b" kept its finished history; "a" re-ran from checkpoint step 2.
    assert by_tag["b"].metrics["step_done"] == 3
    assert by_tag["a"].metrics["step_done"] == 3
    assert by_tag["a"].metrics["start"] == 2  # resumed, not restarted


@pytest.mark.slow
def test_bayesopt_search_converges(ray_tpu_start, tmp_path):
    """GP-EI search concentrates samples near the optimum of a smooth
    1-D objective (ref: BayesOptSearch)."""
    def trainable(config):
        tune.report({"obj": -(config["x"] - 2.0) ** 2})

    search = tune.BayesOptSearch(
        {"x": tune.uniform(-10.0, 10.0)},
        metric="obj", mode="max", n_initial=5, seed=0,
    )
    res = Tuner(
        trainable,
        tune_config=TuneConfig(
            num_samples=20, metric="obj", mode="max", search_alg=search,
            max_concurrent_trials=1,  # sequential: each suggest learns
        ),
        run_config=RunConfig(storage_path=str(tmp_path)),
    ).fit()
    best = res.get_best_result()
    assert abs(best.config["x"] - 2.0) < 0.5, best.config
    # The GP phase (after n_initial) beats pure-random expectation.
    assert best.metrics["obj"] > -0.25


def test_concurrency_limiter_bounds_inflight(ray_tpu_start, tmp_path):
    peak = {"v": 0}

    class Tracking(tune.Searcher):
        def __init__(self):
            super().__init__(metric="m", mode="max")
            self.live = 0

        def suggest(self, trial_id):
            self.live += 1
            peak["v"] = max(peak["v"], self.live)
            return {"i": self.live}

        def on_trial_complete(self, trial_id, result=None, error=False):
            self.live -= 1

    inner = Tracking()
    limited = tune.ConcurrencyLimiter(inner, max_concurrent=2)

    def trainable(config):
        import time

        time.sleep(0.2)
        tune.report({"m": config["i"]})

    Tuner(
        trainable,
        tune_config=TuneConfig(num_samples=6, metric="m", mode="max",
                               search_alg=limited,
                               max_concurrent_trials=4),
        run_config=RunConfig(storage_path=str(tmp_path)),
    ).fit()
    assert peak["v"] <= 2


def test_callbacks_loggers_stoppers(ray_tpu_start, tmp_path):
    """Callback hooks fire through the whole trial lifecycle; CSV/JSON/
    TensorBoard loggers produce per-trial files; a dict stop condition
    ends trials early (ref: tune/callback.py, tune/logger/,
    tune/stopper/)."""
    import os

    from ray_tpu.tune import (
        Callback,
        CSVLoggerCallback,
        JsonLoggerCallback,
        TBXLoggerCallback,
    )

    events = []

    class Recorder(Callback):
        def setup(self, storage_path):
            events.append(("setup", storage_path))

        def on_trial_start(self, trial_id, config):
            events.append(("start", trial_id))

        def on_trial_result(self, trial_id, config, result):
            events.append(("result", trial_id,
                           result["training_iteration"]))

        def on_trial_complete(self, trial_id, result, error=None):
            events.append(("complete", trial_id, error))

        def on_experiment_end(self, results):
            events.append(("end", len(results)))

    def trainable(config):
        import time as _t

        for i in range(10):
            tune.report({"score": float(i)})
            _t.sleep(0.05)

    storage = str(tmp_path / "exp")
    grid = Tuner(
        trainable,
        param_space={"x": tune.grid_search([1, 2])},
        tune_config=TuneConfig(metric="score", mode="max"),
        run_config=RunConfig(
            storage_path=storage,
            callbacks=[Recorder(), CSVLoggerCallback(),
                       JsonLoggerCallback(), TBXLoggerCallback()],
            # dict stop form: end each trial once score reaches 4.
            stop={"score": 4.0},
        ),
    ).fit()
    assert len(grid) == 2
    kinds = [e[0] for e in events]
    assert kinds.count("start") == 2 and kinds.count("complete") == 2
    assert ("end", 2) in events
    # the stopper ended trials well before 10 iterations
    for r in grid:
        assert r.metrics["score"] <= 8.0, r.metrics
    # logger artifacts per trial
    for r in grid:
        d = os.path.join(storage, r.trial_id)
        assert os.path.exists(os.path.join(d, "progress.csv"))
        assert os.path.exists(os.path.join(d, "result.json"))
        assert os.path.exists(os.path.join(d, "params.json"))
        assert any(f.startswith("events.out.tfevents")
                   for f in os.listdir(d)), os.listdir(d)


def test_stoppers_unit():
    """Stopper semantics without a cluster: plateau, max-iteration,
    timeout stop_all, combined OR."""
    from ray_tpu.tune import (
        CombinedStopper,
        MaximumIterationStopper,
        TimeoutStopper,
        TrialPlateauStopper,
    )

    mx = MaximumIterationStopper(3)
    assert not mx("t", {"training_iteration": 2})
    assert mx("t", {"training_iteration": 3})

    pl = TrialPlateauStopper("loss", std=1e-3, num_results=3,
                             grace_period=3)
    assert not pl("t", {"loss": 1.0})
    assert not pl("t", {"loss": 0.5})
    assert not pl("t", {"loss": 0.5})   # grace reached, window [1,.5,.5]
    assert pl("t", {"loss": 0.5})       # window [.5,.5,.5] -> flat

    to = TimeoutStopper(0.0)
    to("t", {})
    assert to.stop_all()

    comb = CombinedStopper(MaximumIterationStopper(100), TimeoutStopper(0.0))
    comb("t", {"training_iteration": 1})
    assert comb.stop_all()


def test_pb2_gp_explore_unit():
    """PB2's GP-bandit explore: with population history the suggested
    hyperparameters stay inside the declared bounds and differ from
    naive perturbation (ref: tune/schedulers/pb2.py)."""
    from ray_tpu.tune import PB2

    pb2 = PB2(
        metric="score", mode="max", perturbation_interval=2,
        hyperparam_bounds={"lr": (1e-4, 1e-1)}, seed=0,
    )
    import numpy as np

    rng = np.random.RandomState(0)
    # Simulate a population: higher lr -> bigger score gains (up to a
    # point), so the GP should suggest lr well above the floor.
    for tid in ("a", "b", "c", "d"):
        lr = float(rng.uniform(1e-4, 1e-1))
        pb2.on_trial_start(tid, {"lr": lr})
        score = 0.0
        for t in range(1, 6):
            score += lr * 10  # monotone improvement in lr
            pb2.on_result(tid, {"training_iteration": t, "score": score})
    out = pb2._explore({"lr": 1e-3})
    assert 1e-4 <= out["lr"] <= 1e-1
    assert len(pb2._gp_rows) >= 8  # GP path actually exercised
    # With a monotone landscape the UCB argmax should sit in the upper
    # half of the range.
    assert out["lr"] > 0.03, out


@pytest.mark.slow
def test_pb2_integration(ray_tpu_start, tmp_path):
    """PB2 drives exploit/explore end to end (checkpoint handoff like
    PBT, GP-suggested configs within bounds)."""
    import time as _time

    from ray_tpu.tune import PB2

    def trainable(config):
        score = 0.0
        for i in range(12):
            score += config["lr"]
            tune.report({"score": score})
            _time.sleep(0.02)

    grid = Tuner(
        trainable,
        param_space={"lr": tune.uniform(0.01, 1.0)},
        tune_config=TuneConfig(
            num_samples=4, metric="score", mode="max",
            scheduler=PB2(metric="score", mode="max",
                          perturbation_interval=3,
                          hyperparam_bounds={"lr": (0.01, 1.0)}),
        ),
        run_config=RunConfig(storage_path=str(tmp_path / "pb2")),
    ).fit()
    assert len(grid) == 4
    best = grid.get_best_result()
    assert best.metrics["score"] > 0
    for r in grid:
        assert 0.01 <= r.config["lr"] <= 1.0


def test_searcher_adapters_gated():
    """Optuna/HyperOpt adapters exist as the plugin surface; without the
    optional packages they fail with a CLEAR ImportError at
    construction (and run for real when the package is present)."""
    from ray_tpu.tune import HyperOptSearch, OptunaSearch

    space = {"x": tune.uniform(0, 1)}
    try:
        import optuna  # noqa: F401

        s = OptunaSearch(space, metric="score", mode="max")
        cfg = s.suggest("t1")
        assert 0 <= cfg["x"] <= 1
        s.on_trial_complete("t1", {"score": 0.5})
    except ImportError:
        with pytest.raises(ImportError, match="optuna"):
            OptunaSearch(space, metric="score", mode="max")
    try:
        import hyperopt  # noqa: F401

        s = HyperOptSearch(space, metric="score", mode="max")
        cfg = s.suggest("t1")
        assert 0 <= cfg["x"] <= 1
    except ImportError:
        with pytest.raises(ImportError, match="hyperopt"):
            HyperOptSearch(space, metric="score", mode="max")


@pytest.mark.slow
def test_tpe_search_converges(ray_tpu_start, tmp_path):
    """Native TPE (the BOHB sampler) concentrates samples near the
    optimum after the random phase (ref: TuneBOHB,
    tune/search/bohb/bohb_search.py)."""
    def trainable(config):
        tune.report({
            "obj": -(config["x"] - 2.0) ** 2
            - (0.0 if config["kind"] == "good" else 4.0)
        })

    search = tune.TPESearch(
        {"x": tune.uniform(-10.0, 10.0),
         "kind": tune.choice(["good", "bad"])},
        metric="obj", mode="max", n_initial=8,
        min_points_in_model=6, seed=0,
    )
    res = Tuner(
        trainable,
        tune_config=TuneConfig(
            num_samples=30, metric="obj", mode="max",
            search_alg=search, max_concurrent_trials=1,
        ),
        run_config=RunConfig(storage_path=str(tmp_path)),
    ).fit()
    best = res.get_best_result()
    assert abs(best.config["x"] - 2.0) < 1.0, best.config
    assert best.config["kind"] == "good"
    assert best.metrics["obj"] > -1.0


@pytest.mark.slow
def test_bohb_scheduler_feeds_searcher(ray_tpu_start, tmp_path):
    """HyperBandForBOHB reports every rung result back to the attached
    TPESearch with its budget (the BOHB coupling, ref:
    tune/schedulers/hb_bohb.py)."""
    def trainable(config):
        for step in range(1, 10):
            tune.report({"obj": config["x"] * step,
                         "training_iteration": step})

    search = tune.TPESearch(
        {"x": tune.uniform(0.0, 1.0)}, metric="obj", mode="max",
        n_initial=4, seed=0,
    )
    scheduler = tune.HyperBandForBOHB(
        metric="obj", mode="max", max_t=9, reduction_factor=3,
        searcher=search,
    )
    Tuner(
        trainable,
        tune_config=TuneConfig(
            num_samples=8, metric="obj", mode="max",
            search_alg=search, scheduler=scheduler,
            max_concurrent_trials=2,
        ),
        run_config=RunConfig(storage_path=str(tmp_path)),
    ).fit()
    # Intermediate budgets observed, not just finals.
    budgets = set(search._obs)
    assert len(budgets) > 1, budgets
    assert sum(len(v) for v in search._obs.values()) >= 8
