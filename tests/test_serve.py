"""Serve tests (ref analogue: python/ray/serve/tests/)."""

import json
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture
def serve_cluster(ray_tpu_start):
    yield ray_tpu_start
    serve.shutdown()


def test_function_deployment(serve_cluster):
    @serve.deployment
    def echo(x):
        return {"echo": x}

    handle = serve.run(echo.bind())
    assert handle.remote("hi").result(timeout=30) == {"echo": "hi"}


def test_class_deployment_with_state(serve_cluster):
    @serve.deployment
    class Model:
        def __init__(self, scale):
            self.scale = scale

        def __call__(self, x):
            return x * self.scale

    handle = serve.run(Model.bind(10))
    assert handle.remote(4).result(timeout=30) == 40


def test_multiple_replicas_all_serve(serve_cluster):
    import os

    @serve.deployment(num_replicas=3)
    class WhoAmI:
        def __call__(self, _):
            return os.getpid()

    handle = serve.run(WhoAmI.bind())
    futs = [handle.remote(None) for _ in range(30)]
    pids = {f.result(timeout=30) for f in futs}
    assert len(pids) >= 2  # p2c spread requests across replicas


def test_scale_up_down(serve_cluster):
    @serve.deployment(num_replicas=1)
    def f(x):
        return x

    serve.run(f.bind(), name="scaled")
    assert serve.status()["scaled"] == 1
    h = serve.scale("scaled", 3)
    assert serve.status()["scaled"] == 3
    assert h.remote(1).result(timeout=30) == 1
    serve.scale("scaled", 1)
    assert serve.status()["scaled"] == 1


def test_dynamic_batching(serve_cluster):
    @serve.deployment
    @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.2)
    def batched(items):
        # One call sees many items (the batch), returns per-item results.
        return [{"n": len(items), "v": x * 2} for x in items]

    handle = serve.run(batched.bind())
    futs = [handle.remote(i) for i in range(8)]
    results = [f.result(timeout=30) for f in futs]
    assert [r["v"] for r in results] == [i * 2 for i in range(8)]
    # At least one flush coalesced multiple requests.
    assert max(r["n"] for r in results) > 1


def test_http_ingress(serve_cluster):
    @serve.deployment
    def double(x):
        return x * 2

    handle = serve.run(double.bind(), route_prefix="double")
    port = handle.http_port
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/double",
        data=json.dumps(21).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=30) as resp:
        body = json.loads(resp.read())
    assert body == {"result": 42}


def test_deployment_error_propagates(serve_cluster):
    @serve.deployment
    def bad(x):
        raise ValueError("replica failed")

    handle = serve.run(bad.bind())
    with pytest.raises(ValueError, match="replica failed"):
        handle.remote(1).result(timeout=30)
