"""Serve tests (ref analogue: python/ray/serve/tests/)."""

import json
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture
def serve_cluster(ray_tpu_start):
    yield ray_tpu_start
    serve.shutdown()


def test_function_deployment(serve_cluster):
    @serve.deployment
    def echo(x):
        return {"echo": x}

    handle = serve.run(echo.bind())
    assert handle.remote("hi").result(timeout=30) == {"echo": "hi"}


def test_class_deployment_with_state(serve_cluster):
    @serve.deployment
    class Model:
        def __init__(self, scale):
            self.scale = scale

        def __call__(self, x):
            return x * self.scale

    handle = serve.run(Model.bind(10))
    assert handle.remote(4).result(timeout=30) == 40


def test_multiple_replicas_all_serve(serve_cluster):
    import os

    @serve.deployment(num_replicas=3)
    class WhoAmI:
        def __call__(self, _):
            return os.getpid()

    handle = serve.run(WhoAmI.bind())
    futs = [handle.remote(None) for _ in range(30)]
    pids = {f.result(timeout=30) for f in futs}
    assert len(pids) >= 2  # p2c spread requests across replicas


def test_scale_up_down(serve_cluster):
    @serve.deployment(num_replicas=1)
    def f(x):
        return x

    serve.run(f.bind(), name="scaled")
    assert serve.status()["scaled"] == 1
    h = serve.scale("scaled", 3)
    assert serve.status()["scaled"] == 3
    assert h.remote(1).result(timeout=30) == 1
    serve.scale("scaled", 1)
    assert serve.status()["scaled"] == 1


def test_dynamic_batching(serve_cluster):
    @serve.deployment
    @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.2)
    def batched(items):
        # One call sees many items (the batch), returns per-item results.
        return [{"n": len(items), "v": x * 2} for x in items]

    handle = serve.run(batched.bind())
    futs = [handle.remote(i) for i in range(8)]
    results = [f.result(timeout=30) for f in futs]
    assert [r["v"] for r in results] == [i * 2 for i in range(8)]
    # At least one flush coalesced multiple requests.
    assert max(r["n"] for r in results) > 1


def test_http_ingress(serve_cluster):
    @serve.deployment
    def double(x):
        return x * 2

    handle = serve.run(double.bind(), route_prefix="double")
    port = handle.http_port
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/double",
        data=json.dumps(21).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=30) as resp:
        body = json.loads(resp.read())
    assert body == {"result": 42}


def test_deployment_error_propagates(serve_cluster):
    @serve.deployment
    def bad(x):
        raise ValueError("replica failed")

    handle = serve.run(bad.bind())
    with pytest.raises(ValueError, match="replica failed"):
        handle.remote(1).result(timeout=30)


@pytest.mark.slow
def test_autoscaling_up_and_down(serve_cluster):
    """AutoscalingConfig drives the replica count from handle queue depth
    (ref: autoscaling_policy.py): load pushes replicas up to max, idleness
    brings them back down to min."""
    import time

    @serve.deployment(
        num_replicas=1,
        autoscaling_config=serve.AutoscalingConfig(
            min_replicas=1,
            max_replicas=3,
            target_ongoing_requests=1.0,
            upscale_delay_s=0.2,
            downscale_delay_s=0.5,
        ),
    )
    def slow(x):
        time.sleep(0.25)
        return x

    handle = serve.run(slow.bind(), name="auto")
    # Sustain enough concurrent load that total outstanding stays >> 1.
    futs = [handle.remote(i) for i in range(40)]
    grew_to = 1
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        grew_to = max(grew_to, serve.status()["auto"])
        if grew_to >= 2:
            break
        time.sleep(0.1)
    assert grew_to >= 2, f"autoscaler never scaled up (peak={grew_to})"
    assert all(f.result(timeout=60) is not None for f in futs)
    # Idle now: expect decay back to min_replicas.
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        if serve.status()["auto"] == 1:
            break
        time.sleep(0.1)
    assert serve.status()["auto"] == 1, "autoscaler never scaled back down"


def test_rolling_redeploy_zero_downtime(serve_cluster):
    """Redeploying new code rolls replicas one at a time; requests issued
    throughout the update all succeed and eventually see the new version
    (ref: deployment_state.py rolling updates)."""
    import threading
    import time

    def make(version):
        @serve.deployment(num_replicas=2)
        def versioned(x):
            return {"version": version, "x": x}

        return versioned

    handle = serve.run(make("v1").bind(), name="roll")
    assert handle.remote(0).result(timeout=30)["version"] == "v1"

    results, errors = [], []
    stop = threading.Event()

    def spam():
        while not stop.is_set():
            try:
                results.append(handle.remote(1).result(timeout=30))
            except Exception as e:  # noqa: BLE001
                errors.append(e)
            time.sleep(0.02)

    t = threading.Thread(target=spam)
    t.start()
    time.sleep(0.3)
    handle2 = serve.run(make("v2").bind(), name="roll")
    # Wait until the new version is being served.
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if results and results[-1]["version"] == "v2":
            break
        time.sleep(0.1)
    time.sleep(0.3)
    stop.set()
    t.join(timeout=30)
    assert not errors, f"requests failed during rolling update: {errors[:3]}"
    versions = {r["version"] for r in results}
    assert "v2" in versions, "update never took effect"
    assert serve.details()["roll"]["replica_versions"] == \
        [serve.details()["roll"]["version"]] * 2
    assert handle2.remote(5).result(timeout=30)["version"] == "v2"


def test_replica_crash_recovery(serve_cluster):
    """A replica whose worker dies is evicted from routing and replaced by
    the controller's health check; callers see retries, not errors (ref:
    deployment_state.py health checks + recovery)."""
    import os
    import time

    @serve.deployment(num_replicas=2)
    class Victim:
        def pid(self, _=None):
            return os.getpid()

        def die_if(self, pid):
            # Targeted kill: retries that land on another replica no-op.
            if os.getpid() == pid:
                os._exit(1)
            return "not me"

        def __call__(self, x):
            return x + 1

    handle = serve.run(Victim.bind(), name="crashy")
    pid_handle = handle.options(method="pid")
    pids = {pid_handle.remote().result(timeout=30) for _ in range(20)}
    assert len(pids) == 2
    # Kill one replica process out from under the router.
    handle.options(method="die_if").remote(next(iter(pids)))
    time.sleep(0.5)
    # Traffic keeps flowing throughout recovery.
    for i in range(20):
        assert handle.remote(i).result(timeout=30) == i + 1
        time.sleep(0.05)
    # Health check replaces the dead replica: back to 2 within its period.
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        if serve.status()["crashy"] == 2:
            break
        time.sleep(0.2)
    assert serve.status()["crashy"] == 2
    new_pids = {pid_handle.remote().result(timeout=30) for _ in range(20)}
    assert len(new_pids) == 2


def test_per_node_proxy_actors(serve_cluster):
    """One proxy actor per node serves HTTP with dynamic route discovery
    (ref: per-node ProxyActor): a deployment created AFTER the proxy
    started is still routable."""
    import urllib.request

    from ray_tpu.serve import http_proxy

    proxies = http_proxy.start_per_node_proxies(port=0)
    try:
        assert len(proxies) >= 1

        @serve.deployment
        def late(x):
            return {"via": "proxy-actor", "x": x}

        serve.run(late.bind(), name="late")
        (_, port), = [v for v in proxies.values()][:1]
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/late",
            data=json.dumps(5).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=30) as resp:
            body = json.loads(resp.read())
        assert body == {"result": {"via": "proxy-actor", "x": 5}}
    finally:
        import ray_tpu

        for actor, _ in proxies.values():
            try:
                ray_tpu.get(actor.shutdown.remote(), timeout=10)
                ray_tpu.kill(actor)
            except Exception:
                pass


def test_model_multiplexing(serve_cluster):
    """@serve.multiplexed: one deployment serves many models with
    per-replica LRU loading and model-affinity routing (ref:
    serve.multiplexed / get_multiplexed_model_id)."""
    import os

    @serve.deployment(num_replicas=2)
    class MultiModel:
        def __init__(self):
            self.loads = 0

        @serve.multiplexed(max_num_models_per_replica=2)
        def load(self, model_id):
            self.loads += 1
            return {"model": model_id, "scale": len(model_id)}

        def __call__(self, x):
            model_id = serve.get_multiplexed_model_id()
            model = self.load(model_id)
            return {"pid": os.getpid(), "model": model["model"],
                    "y": x * model["scale"], "loads": self.loads}

    handle = serve.run(MultiModel.bind(), name="mux")
    mA = handle.options(multiplexed_model_id="modelA")
    out = [mA.remote(2).result(timeout=30) for _ in range(6)]
    assert all(o["model"] == "modelA" and o["y"] == 12 for o in out)
    # Affinity: every modelA request landed on ONE replica, which loaded
    # the model exactly once.
    assert len({o["pid"] for o in out}) == 1
    assert out[-1]["loads"] == 1
    mB = handle.options(multiplexed_model_id="bb")
    assert mB.remote(3).result(timeout=30)["y"] == 6
