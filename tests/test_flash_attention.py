"""Pallas flash attention kernel tests (interpret mode on the CPU mesh;
the same kernel compiles for real on TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.ops import flash_attention, mha_attention


def _rand(shape, key):
    return jax.random.normal(jax.random.PRNGKey(key), shape,
                             dtype=jnp.float32)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_reference(causal):
    B, S, H, D = 2, 256, 4, 64
    q, k, v = (_rand((B, S, H, D), i) for i in range(3))
    ref = mha_attention(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal=causal, interpret=True,
                          block_q=128, block_k=128)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_flash_gqa():
    B, S, H, Hkv, D = 2, 256, 8, 2, 64
    q = _rand((B, S, H, D), 0)
    k = _rand((B, S, Hkv, D), 1)
    v = _rand((B, S, Hkv, D), 2)
    ref = mha_attention(q, k, v, causal=True)
    out = flash_attention(q, k, v, causal=True, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_flash_decode_offsets():
    """Global-coordinate masking: a single query block at q_offset against
    a long KV prefix (the decode/ring-attention case)."""
    B, H, D = 1, 4, 64
    Skv, Sq, q_off = 512, 128, 384
    q = _rand((B, Sq, H, D), 0)
    k = _rand((B, Skv, H, D), 1)
    v = _rand((B, Skv, H, D), 2)
    ref = mha_attention(q, k, v, causal=True, q_offset=q_off)
    out = flash_attention(q, k, v, causal=True, q_offset=q_off,
                          interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_flash_gradients_match():
    B, S, H, D = 1, 128, 2, 32
    q, k, v = (_rand((B, S, H, D), i) for i in range(3))

    def loss_flash(q, k, v):
        return flash_attention(q, k, v, causal=True,
                               interpret=True).sum()

    def loss_ref(q, k, v):
        return mha_attention(q, k, v, causal=True).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-5, rtol=2e-5)


def test_flash_fallback_for_odd_shapes():
    # Non-tileable sequence length silently takes the XLA path.
    B, S, H, D = 1, 100, 2, 32
    q, k, v = (_rand((B, S, H, D), i) for i in range(3))
    out = flash_attention(q, k, v, causal=True)
    ref = mha_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-6)


def test_flash_gqa_gradients_match():
    """Backward with grouped KV heads: dK/dV must sum each group's query
    heads (reduced inside the grouped dkv kernel)."""
    B, S, H, Hkv, D = 1, 128, 4, 2, 32
    q = _rand((B, S, H, D), 0)
    k = _rand((B, S, Hkv, D), 1)
    v = _rand((B, S, Hkv, D), 2)

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, causal=True,
                                interpret=True) ** 2).sum()

    def loss_ref(q, k, v):
        return (mha_attention(q, k, v, causal=True) ** 2).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=3e-5, rtol=3e-5)


def test_flash_decode_offset_gradients():
    """Fused backward with nonzero q_offset (the block-bound math must
    stay consistent with the forward's)."""
    B, S, Skv, H, D = 1, 128, 256, 2, 32
    q = _rand((B, S, H, D), 3)
    k = _rand((B, Skv, H, D), 4)
    v = _rand((B, Skv, H, D), 5)

    def loss_flash(q, k, v):
        return flash_attention(q, k, v, causal=True, q_offset=128,
                               interpret=True).sum()

    def loss_ref(q, k, v):
        return mha_attention(q, k, v, causal=True, q_offset=128).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-5, rtol=2e-5)


def test_flash_gqa_gradients_perhead_fallback(monkeypatch):
    """Shapes whose grouped [rep, Sq, D] Q/dO block would overflow VMEM
    use the per-query-head dkv kernel + external group sum; force that
    path by zeroing the VMEM budget and check grads still match XLA."""
    import importlib

    fa = importlib.import_module("ray_tpu.ops.flash_attention")
    monkeypatch.setattr(fa, "_DKV_GROUP_VMEM_BUDGET", 0)
    B, S, H, Hkv, D = 1, 128, 4, 2, 32
    q = _rand((B, S, H, D), 0)
    k = _rand((B, S, Hkv, D), 1)
    v = _rand((B, S, Hkv, D), 2)

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, causal=True,
                                interpret=True) ** 2).sum()

    def loss_ref(q, k, v):
        return (mha_attention(q, k, v, causal=True) ** 2).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=3e-5, rtol=3e-5)
