"""TorchTrainer: gloo process group, DDP gradient averaging,
session/checkpoint flow shared with JaxTrainer.

Ref analogue: train/torch/torch_trainer.py + config.py
_setup_torch_process_group (gloo on CPU, as the reference's own CPU
tests run it) + train_loop_utils prepare_model/prepare_data_loader.
"""

import sys as _sys

import cloudpickle as _cloudpickle
import numpy as np
import pytest

import ray_tpu.train as rt_train
from ray_tpu.train import RunConfig, ScalingConfig, TorchTrainer

_cloudpickle.register_pickle_by_value(_sys.modules[__name__])


@pytest.mark.slow
def test_torch_trainer_ddp_allreduce(ray_tpu_start, tmp_path):
    """Two workers join one gloo group; DDP averages gradients so both
    ranks hold identical updated weights after a step on different
    data."""
    pytest.importorskip("torch")

    def loop(config):
        import torch
        import torch.distributed as dist

        from ray_tpu.train.torch import prepare_model

        rank = rt_train.get_world_rank()
        assert dist.is_initialized()
        assert dist.get_world_size() == 2

        torch.manual_seed(0)  # same init on both ranks
        model = prepare_model(torch.nn.Linear(4, 1))
        opt = torch.optim.SGD(model.parameters(), lr=0.1)
        # Different data per rank -> DDP must allreduce gradients.
        torch.manual_seed(rank + 1)
        x = torch.randn(8, 4)
        y = torch.randn(8, 1)
        loss = torch.nn.functional.mse_loss(model(x), y)
        loss.backward()
        opt.step()
        w = model.module.weight.detach().numpy().copy()
        # Weights must MATCH across ranks (averaged grads).
        gathered = [torch.zeros(4) for _ in range(2)]
        dist.all_gather(gathered, torch.from_numpy(w[0]))
        np.testing.assert_allclose(
            gathered[0].numpy(), gathered[1].numpy(), atol=1e-6
        )
        rt_train.report({
            "rank": rank,
            "loss": float(loss),
            "w0": float(w[0, 0]),
        })

    result = TorchTrainer(
        loop,
        train_loop_config={},
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(storage_path=str(tmp_path / "torch_ddp")),
    ).fit()
    assert result.error is None, result.error
    assert result.metrics["rank"] == 0
    assert np.isfinite(result.metrics["loss"])


def test_torch_trainer_single_worker_no_group(ray_tpu_start, tmp_path):
    """World size 1: no process group, prepare_model passes through."""
    pytest.importorskip("torch")

    def loop(config):
        import torch
        import torch.distributed as dist

        from ray_tpu.train.torch import prepare_model

        assert not dist.is_initialized()
        model = prepare_model(torch.nn.Linear(2, 1))
        assert isinstance(model, torch.nn.Linear)  # unwrapped
        rt_train.report({"ok": 1})

    result = TorchTrainer(
        loop,
        train_loop_config={},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(storage_path=str(tmp_path / "torch_1")),
    ).fit()
    assert result.error is None
    assert result.metrics["ok"] == 1


@pytest.mark.slow
def test_torch_prepare_data_loader(ray_tpu_start, tmp_path):
    """prepare_data_loader shards the dataset: each rank sees half."""
    pytest.importorskip("torch")

    def loop(config):
        import torch
        from torch.utils.data import DataLoader, TensorDataset

        from ray_tpu.train.torch import prepare_data_loader

        ds = TensorDataset(torch.arange(16).float()[:, None])
        dl = prepare_data_loader(DataLoader(ds, batch_size=4))
        seen = sum(len(b[0]) for b in dl)
        rt_train.report({"seen": seen,
                         "rank": rt_train.get_world_rank()})

    result = TorchTrainer(
        loop,
        train_loop_config={},
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(storage_path=str(tmp_path / "torch_dl")),
    ).fit()
    assert result.error is None
    assert result.metrics["seen"] == 8  # 16 rows / 2 ranks
