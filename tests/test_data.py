"""ray_tpu.data tests (ref analogue: python/ray/data/tests/)."""

import numpy as np
import pytest

from ray_tpu import data as rd


def test_range_count_take():
    ds = rd.range(100)
    assert ds.count() == 100
    rows = ds.take(5)
    assert [r["id"] for r in rows] == [0, 1, 2, 3, 4]


def test_from_items():
    ds = rd.from_items([{"a": i, "b": i * 2} for i in range(10)])
    assert ds.count() == 10
    assert sorted(r["a"] for r in ds.take_all()) == list(range(10))


def test_map_batches_numpy():
    ds = rd.range(32).map_batches(lambda b: {"x": b["id"] * 2})
    out = ds.to_numpy()
    np.testing.assert_array_equal(np.sort(out["x"]),
                                  np.arange(32, dtype=np.int64) * 2)


def test_map_and_filter_rows():
    ds = rd.range(20).map(lambda r: {"v": int(r["id"]) + 1})
    ds = ds.filter(lambda r: r["v"] % 2 == 0)
    vals = sorted(r["v"] for r in ds.take_all())
    assert vals == [2, 4, 6, 8, 10, 12, 14, 16, 18, 20]


def test_flat_map():
    ds = rd.from_items([{"x": 1}, {"x": 2}]).flat_map(
        lambda r: [{"y": r["x"]}, {"y": r["x"] * 10}]
    )
    assert sorted(r["y"] for r in ds.take_all()) == [1, 2, 10, 20]


def test_iter_batches_sizes():
    ds = rd.range(100, override_num_blocks=7)
    sizes = [len(b["id"]) for b in ds.iter_batches(batch_size=32)]
    assert sum(sizes) == 100
    assert all(s == 32 for s in sizes[:-1])


def test_tensor_columns_roundtrip():
    imgs = np.random.RandomState(0).randint(0, 255, (10, 8, 8, 3),
                                            dtype=np.uint8)
    ds = rd.from_numpy(imgs, column="image")
    out = ds.to_numpy()["image"]
    np.testing.assert_array_equal(np.sort(out.ravel()),
                                  np.sort(imgs.ravel()))
    assert out.shape == (10, 8, 8, 3)


def test_sort_and_limit():
    ds = rd.from_items([{"k": i % 5, "v": i} for i in range(20)])
    s = ds.sort("v", descending=True)
    assert [r["v"] for r in s.take(3)] == [19, 18, 17]
    assert ds.limit(7).count() == 7


def test_random_shuffle_preserves_rows():
    ds = rd.range(50).random_shuffle(seed=42)
    assert sorted(r["id"] for r in ds.take_all()) == list(range(50))


def test_repartition():
    ds = rd.range(30).repartition(3)
    assert ds.num_blocks() == 3
    assert ds.count() == 30


def test_groupby_aggregates():
    ds = rd.from_items([{"k": i % 3, "v": float(i)} for i in range(12)])
    counts = {r["k"]: r["count()"] for r in ds.groupby("k").count().take_all()}
    assert counts == {0: 4, 1: 4, 2: 4}
    sums = {r["k"]: r["sum(v)"] for r in ds.groupby("k").sum("v").take_all()}
    assert sums[0] == 0 + 3 + 6 + 9


def test_streaming_split_shards():
    ds = rd.range(40, override_num_blocks=8)
    shards = ds.streaming_split(4)
    total = sum(s.count() for s in shards)
    assert total == 40
    assert all(s.count() == 10 for s in shards)


def test_csv_parquet_roundtrip(tmp_path):
    import pyarrow as pa
    import pyarrow.parquet as pq

    table = pa.table({"a": list(range(20)), "b": [i * 1.5 for i in range(20)]})
    pq.write_table(table, str(tmp_path / "f1.parquet"))
    pq.write_table(table, str(tmp_path / "f2.parquet"))
    ds = rd.read_parquet(str(tmp_path) + "/*.parquet")
    assert ds.count() == 40
    assert ds.num_blocks() == 2

    import csv

    with open(tmp_path / "data.csv", "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["x", "y"])
        for i in range(10):
            w.writerow([i, i * i])
    ds2 = rd.read_csv(str(tmp_path / "data.csv"))
    assert ds2.count() == 10
    assert ds2.take(3)[2]["y"] == 4


def test_distributed_execution(ray_tpu_start):
    """Blocks execute as remote tasks when the runtime is up."""
    ds = rd.range(64, override_num_blocks=8).map_batches(
        lambda b: {"sq": b["id"] ** 2}
    )
    out = np.sort(ds.to_numpy()["sq"])
    np.testing.assert_array_equal(out, (np.arange(64) ** 2))


def test_iter_jax_batches():
    pytest.importorskip("jax")
    ds = rd.range(32).map_batches(lambda b: {"x": b["id"].astype(np.float32)})
    batches = list(ds.iter_jax_batches(batch_size=16))
    assert len(batches) == 2
    import jax

    assert isinstance(batches[0]["x"], jax.Array)


def test_trainer_dataset_integration(ray_tpu_start, tmp_path):
    """Dataset shards flow into train workers via get_dataset_shard."""
    import ray_tpu
    from ray_tpu import train as rt_train
    from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig

    ds = rd.range(40, override_num_blocks=8)

    def loop():
        shard = rt_train.get_dataset_shard("train")
        n = sum(len(b["id"]) for b in shard.iter_batches(batch_size=10))
        rt_train.report({"rows": n, "rank": rt_train.get_world_rank()})

    result = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(storage_path=str(tmp_path / "di")),
        datasets={"train": ds},
    ).fit()
    assert result.error is None, result.error
    assert result.metrics["rows"] == 20


@pytest.mark.slow
def test_distributed_shuffle_and_sort(ray_tpu_start):
    """random_shuffle / sort / repartition run as two-stage shuffles over
    remote tasks: partitions live in the object store, not the driver."""
    ds = rd.range(200, override_num_blocks=8)
    sh = ds.random_shuffle(seed=7)
    vals = [r["id"] for r in sh.take_all()]
    assert sorted(vals) == list(range(200))
    assert vals != list(range(200))  # actually permuted

    st = ds.sort("id", descending=True)
    got = [r["id"] for r in st.take_all()]
    assert got == list(range(199, -1, -1))

    rp = ds.repartition(5)
    assert rp.num_blocks() == 5
    assert sorted(r["id"] for r in rp.take_all()) == list(range(200))


def test_shuffle_checksum_across_transforms(ray_tpu_start):
    """Shuffle output feeds further lazy transforms; row count + checksum
    survive the exchange."""
    ds = rd.range(300, override_num_blocks=6).map_batches(
        lambda b: {"v": b["id"] * 3}
    )
    out = ds.random_shuffle(seed=1).map_batches(
        lambda b: {"v": b["v"] + 1}
    )
    arr = np.sort(out.to_numpy()["v"])
    np.testing.assert_array_equal(arr, np.arange(300) * 3 + 1)


def test_write_sinks_roundtrip(ray_tpu_start, tmp_path):
    """write_parquet/csv/json: one file per block written by remote
    tasks, readable back with matching contents."""
    ds = rd.range(50, override_num_blocks=4).map_batches(
        lambda b: {"a": b["id"], "b": b["id"] * 0.5}
    )
    pq_files = ds.write_parquet(str(tmp_path / "pq"))
    assert len(pq_files) == 4
    back = rd.read_parquet(str(tmp_path / "pq") + "/*.parquet")
    assert back.count() == 50
    assert np.isclose(np.sort(back.to_numpy()["b"]).sum(),
                      (np.arange(50) * 0.5).sum())

    csv_files = ds.write_csv(str(tmp_path / "csv"))
    assert len(csv_files) == 4
    back_csv = rd.read_csv(str(tmp_path / "csv") + "/*.csv")
    assert back_csv.count() == 50

    json_files = ds.write_json(str(tmp_path / "js"))
    import json

    rows = []
    for f in json_files:
        with open(f) as fh:
            rows += [json.loads(line) for line in fh]
    assert sorted(r["a"] for r in rows) == list(range(50))


def test_actor_pool_map_batches(ray_tpu_start):
    """A class passed to map_batches becomes a stateful actor-pool stage:
    the class constructs once per pool member, not once per block (ref:
    actor_pool_map_operator.py)."""
    import os

    class AddModel:
        def __init__(self, delta):
            # Expensive-to-build state, constructed once per actor.
            self.delta = delta
            self.pid = os.getpid()

        def __call__(self, batch):
            return {"y": batch["id"] + self.delta, "pid":
                    np.full(len(batch["id"]), self.pid)}

    ds = rd.range(80, override_num_blocks=8).map_batches(
        AddModel, concurrency=2, fn_constructor_args=(100,)
    )
    out = ds.to_numpy()
    assert sorted(out["y"].tolist()) == list(range(100, 180))
    # 8 blocks flowed through at most 2 distinct actor processes.
    assert len(set(out["pid"].tolist())) <= 2


def test_preprocessors():
    from ray_tpu.data.preprocessors import (
        Chain,
        Concatenator,
        LabelEncoder,
        MinMaxScaler,
        StandardScaler,
    )

    ds = rd.from_items(
        [{"x": float(i), "y": float(i * 2), "label": "ab"[i % 2]}
         for i in range(10)]
    )
    sc = StandardScaler(["x"]).fit(ds)
    out = sc.transform(ds).to_numpy()["x"]
    assert abs(out.mean()) < 1e-6 and abs(out.std() - 1.0) < 1e-6

    mm = MinMaxScaler(["y"]).fit(ds)
    out2 = mm.transform(ds).to_numpy()["y"]
    assert out2.min() == 0.0 and out2.max() == 1.0

    le = LabelEncoder("label").fit(ds)
    codes = le.transform(ds).to_numpy()["label"]
    assert set(codes.tolist()) == {0, 1}

    chain = Chain(
        StandardScaler(["x"]), Concatenator(["x", "y"],
                                            output_column_name="feat")
    ).fit(ds)
    feat = chain.transform(ds).to_numpy()["feat"]
    assert feat.shape == (10, 2)
    # Serving-time single-batch path.
    one = chain.transform_batch({"x": np.asarray([0.0]),
                                 "y": np.asarray([3.0]),
                                 "label": np.asarray(["a"])})
    assert one["feat"].shape == (1, 2)


def test_groupby_distributed_combiners(ray_tpu_start):
    """Aggregates run as per-block combiners merged on the driver; the
    dataset never materializes centrally."""
    ds = rd.range(1000, override_num_blocks=8).map_batches(
        lambda b: {"k": b["id"] % 5, "v": b["id"].astype(np.float64)}
    )
    out = {r["k"]: r["sum(v)"] for r in ds.groupby("k").sum("v").take_all()}
    expected = {}
    for i in range(1000):
        expected[i % 5] = expected.get(i % 5, 0.0) + float(i)
    assert out == expected
    means = {r["k"]: r["mean(v)"]
             for r in ds.groupby("k").mean("v").take_all()}
    assert all(abs(means[k] - expected[k] / 200) < 1e-9 for k in means)
    counts = {r["k"]: r["count()"]
              for r in ds.groupby("k").count().take_all()}
    assert all(c == 200 for c in counts.values())


@pytest.mark.slow
def test_map_groups_via_hash_shuffle(ray_tpu_start):
    ds = rd.range(100, override_num_blocks=5).map_batches(
        lambda b: {"k": b["id"] % 4, "v": b["id"]}
    )

    def summarize(group):
        return {"k": [int(group["k"][0])],
                "total": [int(group["v"].sum())]}

    out = {r["k"]: r["total"]
           for r in ds.groupby("k").map_groups(summarize).take_all()}
    expected = {}
    for i in range(100):
        expected[i % 4] = expected.get(i % 4, 0) + i
    assert out == expected


def test_groupby_string_minmax_and_int_sums(ray_tpu_start):
    ds = rd.from_items(
        [{"k": i % 2, "name": "abcdef"[i % 6], "v": int(i)}
         for i in range(60)]
    )
    mins = {r["k"]: r["min(name)"]
            for r in ds.groupby("k").min("name").take_all()}
    assert mins == {0: "a", 1: "b"}
    sums = {r["k"]: r["sum(v)"]
            for r in ds.groupby("k").sum("v").take_all()}
    assert sums[0] + sums[1] == sum(range(60))
    assert all(isinstance(v, (int, np.integer)) for v in sums.values())
    import pytest as _pytest

    with _pytest.raises(TypeError, match="non-numeric"):
        ds.groupby("k").sum("name").take_all()


def test_read_write_tfrecords(ray_tpu_start, tmp_path):
    """TFRecord sink + source roundtrip (dependency-free Example codec;
    ref: ray.data.read_tfrecords / write_tfrecords)."""
    ds = rd.from_items(
        [{"x": i, "y": i / 2, "tag": f"r{i}"} for i in range(30)],
        override_num_blocks=3,
    )
    out = str(tmp_path / "tfr")
    files = ds.write_tfrecords(out)
    assert len(files) == 3
    back = rd.read_tfrecords([out + "/*.tfrecord"])
    rows = sorted(back.take_all(), key=lambda r: r["x"])
    assert len(rows) == 30
    assert rows[7]["x"] == 7 and abs(rows[7]["y"] - 3.5) < 1e-6
    assert rows[7]["tag"] == b"r7"  # bytes_list, tf semantics


def test_read_write_avro(ray_tpu_start, tmp_path):
    """Avro OCF sink + source roundtrip (dependency-free codec with
    deflate blocks; ref: ray.data.read_avro over
    datasource/avro_datasource.py)."""
    ds = rd.from_items(
        [{"x": i, "y": i / 2, "tag": f"r{i}", "ok": i % 2 == 0,
          "maybe": None if i % 3 == 0 else i}
         for i in range(30)],
        override_num_blocks=3,
    )
    out = str(tmp_path / "avro")
    files = ds.write_avro(out)
    assert len(files) == 3
    back = rd.read_avro([out + "/*.avro"])
    rows = sorted(back.take_all(), key=lambda r: r["x"])
    assert len(rows) == 30
    assert rows[7]["x"] == 7 and abs(rows[7]["y"] - 3.5) < 1e-6
    assert rows[7]["tag"] == "r7" and not rows[7]["ok"]
    assert rows[6]["maybe"] is None and rows[7]["maybe"] == 7


def test_avro_codec_unit(tmp_path):
    """Codec features beyond the tabular path: null codec, explicit
    schemas with arrays/maps/enums/unions, schema inference."""
    from ray_tpu.data.avro import (
        infer_schema,
        read_avro_file,
        write_avro_file,
    )

    schema = {
        "type": "record", "name": "R", "fields": [
            {"name": "id", "type": "long"},
            {"name": "xs", "type": {"type": "array", "items": "double"}},
            {"name": "m", "type": {"type": "map", "values": "string"}},
            {"name": "color", "type": {"type": "enum", "name": "C",
                                       "symbols": ["RED", "BLUE"]}},
            {"name": "opt", "type": ["null", "string"]},
        ],
    }
    rows = [
        {"id": 1, "xs": [1.0, 2.5], "m": {"a": "b"}, "color": "RED",
         "opt": None},
        {"id": -2, "xs": [], "m": {}, "color": "BLUE", "opt": "yes"},
    ]
    p = str(tmp_path / "u.avro")
    write_avro_file(p, rows, schema=schema, codec="null")
    assert read_avro_file(p) == rows

    # inference widens int+float, unions nullables
    s = infer_schema([{"a": 1, "b": None}, {"a": 2.0, "b": "x"}])
    by_name = {f["name"]: f["type"] for f in s["fields"]}
    assert by_name["a"] == "double"
    assert by_name["b"] == ["null", "string"]


def test_read_sql(ray_tpu_start, tmp_path):
    """read_sql over a DBAPI connection factory, sharded by blocks
    (ref: ray.data.read_sql)."""
    import sqlite3

    db = str(tmp_path / "t.db")
    conn = sqlite3.connect(db)
    conn.execute("CREATE TABLE m (k TEXT, v REAL)")
    conn.executemany("INSERT INTO m VALUES (?, ?)",
                     [(f"k{i:02d}", i * 1.5) for i in range(20)])
    conn.commit()
    conn.close()
    ds = rd.read_sql("SELECT k, v FROM m ORDER BY k",
                     lambda: sqlite3.connect(db), override_num_blocks=3)
    rows = sorted(ds.take_all(), key=lambda r: r["k"])
    assert len(rows) == 20
    assert rows[4] == {"k": "k04", "v": 6.0}


def test_per_operator_stats(ray_tpu_start):
    """ds.stats() prints per-stage wall/rows/bytes after an executed
    pipeline (VERDICT r3 ask #10; ref: data/_internal/stats.py)."""
    ds = rd.range(500, override_num_blocks=4).map_batches(
        lambda b: {"id": b["id"], "sq": b["id"] ** 2}
    ).filter(lambda r: r["id"] % 2 == 0)
    rows = ds.take_all()
    assert len(rows) == 250
    report = ds.stats()
    assert "MapBatches" in report and "FilterRows" in report
    assert "250 rows" in report and "blocks" in report
    assert "Total wall" in report and "bytes" in report


def test_random_access_dataset(ray_tpu_start):
    """to_random_access: range-partitioned actor pool with point lookups
    and batched multiget (ref: random_access_dataset.py)."""
    ds = rd.from_items(
        [{"id": i, "val": i * 10} for i in range(100)],
        override_num_blocks=4,
    )
    ra = ds.to_random_access("id", num_workers=3)
    try:
        assert ra.get(42) == {"id": 42, "val": 420}
        assert ra.get(-5) is None
        got = ra.multiget([7, 99, 0, 1000, 55])
        assert [g["val"] if g else None for g in got] == \
            [70, 990, 0, None, 550]
        st = ra.stats()
        assert st["total_rows"] == 100 and st["num_partitions"] == 3
    finally:
        ra.destroy()


def test_read_write_webdataset(ray_tpu_start, tmp_path):
    """WebDataset tar shards: grouped-by-basename samples roundtrip with
    per-extension decode (ref: ray.data.read_webdataset /
    write_webdataset; stdlib-tar codec in data/webdataset.py)."""
    ds = rd.from_items(
        [{"__key__": f"{i:04d}", "jpg": bytes([i, 255 - i]),
          "cls": i % 5, "json": {"idx": i}} for i in range(20)],
        override_num_blocks=2,
    )
    out = str(tmp_path / "wds")
    files = ds.write_webdataset(out)
    assert len(files) == 2 and all(f.endswith(".tar") for f in files)
    back = rd.read_webdataset([out + "/*.tar"])
    rows = sorted(back.take_all(), key=lambda r: r["__key__"])
    assert len(rows) == 20
    assert rows[7]["cls"] == 2
    assert bytes(rows[7]["jpg"]) == bytes([7, 248])
    j = rows[7]["json"]
    assert (j == {"idx": 7}) or (dict(j).get("idx") == 7)


def test_webdataset_edge_payloads(ray_tpu_start, tmp_path):
    """Review regressions: trailing-NUL bytes survive, optional fields
    missing from the first sample are not dropped, directory-distinct
    samples stay distinct, dotted keys are rejected at write time."""
    import tarfile as _tar

    from ray_tpu.data.webdataset import read_shard, write_shard

    out = str(tmp_path / "edge")
    ds = rd.from_items([
        {"__key__": "0000", "jpg": b"\x01\x00\x00"},           # NUL tail
        {"__key__": "0001", "jpg": b"\x02", "json": {"i": 1}},  # optional
    ], override_num_blocks=1)
    files = ds.write_webdataset(out)
    rows = sorted(rd.read_webdataset([out + "/*.tar"]).take_all(),
                  key=lambda r: r["__key__"])
    assert bytes(rows[0]["jpg"]) == b"\x01\x00\x00"
    assert rows[0]["json"] is None
    j = rows[1]["json"]
    assert (j == {"i": 1}) or (dict(j).get("i") == 1)

    with pytest.raises(Exception):
        write_shard(str(tmp_path / "bad.tar"),
                    iter([{"__key__": "img.v2", "cls": 1}]))

    # directory-distinct samples with the same basename
    p = str(tmp_path / "dirs.tar")
    import io as _io

    with _tar.open(p, "w") as tf:
        for name, data in (("a/0001.cls", b"1"), ("b/0001.cls", b"2")):
            info = _tar.TarInfo(name=name)
            info.size = len(data)
            tf.addfile(info, _io.BytesIO(data))
    got = read_shard(p)
    assert len(got) == 2 and {r["cls"] for r in got} == {1, 2}
    assert {r["__key__"] for r in got} == {"a/0001", "b/0001"}


def test_zero_copy_read_path_and_dlpack(ray_tpu_start):
    """The block read path stays zero-copy end to end (SURVEY.md §5.8):
    arrow->numpy views the store pages (incl. SLICED blocks via the
    FixedSizeList offset window), and iter_jax_batches(zero_copy=True)
    aliases them into jax via dlpack on the CPU backend."""
    import jax
    import numpy as np

    from ray_tpu.data.context import DataContext

    if jax.default_backend() != "cpu":
        import pytest as _pytest

        _pytest.skip("dlpack aliasing is exercised on the CPU backend")
    arr = np.arange(64 * 128, dtype=np.float32).reshape(64, 128)
    ds = rd.from_numpy(arr, override_num_blocks=2).materialize()
    old = DataContext.get_current().use_remote_tasks
    DataContext.get_current().use_remote_tasks = False
    try:
        # Sliced batches (batch smaller than block): offset window must
        # produce the right rows with no copy mistakes.
        batches = list(ds.iter_batches(batch_size=24, drop_last=False))
        got = np.concatenate([b["data"] for b in batches])
        np.testing.assert_array_equal(got, arr)

        # dlpack aliasing: the jax array shares the store pages.
        out = []
        for jb in ds.iter_jax_batches(batch_size=32, zero_copy=True):
            out.append(np.asarray(jb["data"]))
        np.testing.assert_array_equal(np.concatenate(out), arr)
    finally:
        DataContext.get_current().use_remote_tasks = old


def test_dlpack_alias_pins_and_values(ray_tpu_start):
    """_dlpack_alias: readonly store views export through dlpack with a
    live reference chain; values match and the alias is not a copy."""
    import numpy as np

    import ray_tpu
    from ray_tpu.data.dataset import _dlpack_alias
    big = np.random.RandomState(3).rand(100_000).astype(np.float32)
    v = ray_tpu.get(ray_tpu.put(big))
    w = _dlpack_alias(v)
    assert w.ctypes.data == v.ctypes.data  # same memory, no copy
    np.testing.assert_array_equal(w, big)
    # chain: alias -> (view levels) -> ctypes buffer -> original view
    base, pin = w, None
    while base is not None and pin is None:
        pin = getattr(base, "_rtpu_pin", None)
        base = getattr(base, "base", None)
    assert pin is v


def test_from_huggingface():
    """HF datasets are arrow-backed; from_huggingface slices the table
    zero-copy into blocks (ref: ray.data.from_huggingface)."""
    import datasets as hf

    ds_hf = hf.Dataset.from_dict(
        {"text": [f"doc-{i}" for i in range(20)],
         "label": list(range(20))}
    )
    ds = rd.from_huggingface(ds_hf, override_num_blocks=4)
    assert ds.num_blocks() == 4
    assert ds.count() == 20
    rows = ds.take_all()
    assert rows[0]["text"] == "doc-0" and rows[19]["label"] == 19
    # split selection guard
    dd = hf.DatasetDict({"train": ds_hf})
    with pytest.raises(ValueError, match="split"):
        rd.from_huggingface(dd)


def test_read_bigquery_fake_client():
    """read_bigquery with an injected client (the real default is
    google.cloud.bigquery.Client): arrow results shard into blocks."""
    import pyarrow as pa

    class FakeJob:
        def __init__(self, sql):
            self.sql = sql

        def to_arrow(self):
            return pa.table({"id": list(range(10)),
                             "v": [i * 2 for i in range(10)]})

    class FakeClient:
        def query(self, sql):
            assert "SELECT" in sql
            return FakeJob(sql)

    ds = rd.read_bigquery("SELECT id, v FROM t",
                          client_factory=FakeClient)
    rows = ds.take_all()
    assert len(rows) == 10
    assert sorted(r["id"] for r in rows) == list(range(10))
    assert all(r["v"] == 2 * r["id"] for r in rows)
    # dataset= form builds the full-table query
    ds2 = rd.read_bigquery(dataset="proj.ds.table",
                           client_factory=FakeClient)
    assert ds2.count() == 10
    # parallel reads = EXPLICIT disjoint shard queries, one block each
    ds3 = rd.read_bigquery(
        queries=["SELECT id, v FROM t WHERE id < 5",
                 "SELECT id, v FROM t WHERE id >= 5"],
        client_factory=FakeClient)
    assert ds3.num_blocks() == 2 and ds3.count() == 20


def test_read_mongo_fake_client():
    """read_mongo with an injected client (pymongo optional): documents
    shard stably and _id is dropped."""

    class FakeCursor:
        def __init__(self, docs):
            self.docs = docs

        def sort(self, key, direction):
            return FakeCursor(sorted(self.docs, key=lambda d: d[key]))

        def skip(self, n):
            return FakeCursor(self.docs[n:])

        def limit(self, n):
            return FakeCursor(self.docs[:n])

        def __iter__(self):
            return iter(self.docs)

    def _docs(q):
        docs = [{"_id": i, "kind": "a" if i % 2 else "b", "n": i}
                for i in range(8)]
        if q:
            docs = [d for d in docs if d["kind"] == q["kind"]]
        return docs

    class FakeColl:
        def find(self, q):
            assert q == {} or q == {"kind": "a"}
            return FakeCursor(_docs(q))

        def count_documents(self, q):
            return len(_docs(q))

    class FakeClient(dict):
        def __init__(self):
            super().__init__(db={"coll": FakeColl()})

        def __getitem__(self, k):
            return {"coll": FakeColl()}

    ds = rd.read_mongo(database="db", collection="coll",
                       client_factory=FakeClient,
                       override_num_blocks=2)
    rows = ds.take_all()
    assert len(rows) == 8 and all("_id" not in r for r in rows)
    ds2 = rd.read_mongo(database="db", collection="coll",
                        query={"kind": "a"},
                        client_factory=FakeClient)
    assert ds2.count() == 4


@pytest.mark.slow
def test_push_based_shuffle_parity(ray_tpu_start):
    """Push-based shuffle (rounds of maps + merge stage) produces
    byte-identical results to the simple plan for random_shuffle, sort
    and repartition (ref: _internal/push_based_shuffle.py)."""
    from ray_tpu.data.context import DataContext

    ctx = DataContext.get_current()
    n = 200
    base = rd.from_items(
        [{"k": i % 7, "v": float(i)} for i in range(n)],
        override_num_blocks=20,
    )

    def checksum(ds):
        rows = ds.take_all()
        return (sorted(round(r["v"], 6) for r in rows),
                sorted(r["k"] for r in rows))

    old = ctx.push_based_shuffle
    try:
        ctx.push_based_shuffle = False
        simple_shuf = checksum(base.random_shuffle(seed=7))
        simple_sorted = [r["v"] for r in base.sort("v").take_all()]
        simple_rep = checksum(base.repartition(5))

        ctx.push_based_shuffle = True
        push_shuf = checksum(base.random_shuffle(seed=7))
        push_sorted = [r["v"] for r in base.sort("v").take_all()]
        push_rep = checksum(base.repartition(5))
        push_group = base.groupby("k").map_groups(
            lambda g: {"k": g["k"][:1], "s": [float(sum(g["v"]))]}
        ).take_all()
    finally:
        ctx.push_based_shuffle = old

    assert push_shuf == simple_shuf
    assert push_sorted == simple_sorted == sorted(
        float(i) for i in range(n)
    )
    assert push_rep == simple_rep
    assert sum(r["s"] for r in push_group) == sum(
        float(i) for i in range(n)
    )


def test_split_apis(ray_tpu_start):
    """split_at_indices / split_proportionately / train_test_split
    (ref: dataset.split_at_indices etc.)."""
    ds = rd.range(20, override_num_blocks=3).map_batches(
        lambda b: {"x": b["id"]}
    )
    a, b, c = ds.split_at_indices([5, 12])
    assert [r["x"] for r in a.take_all()] == list(range(5))
    assert [r["x"] for r in b.take_all()] == list(range(5, 12))
    assert [r["x"] for r in c.take_all()] == list(range(12, 20))

    p, q, rest = ds.split_proportionately([0.25, 0.25])
    assert p.count() == 5 and q.count() == 5 and rest.count() == 10

    train, test = ds.train_test_split(0.3)
    assert train.count() == 14 and test.count() == 6
    train_s, test_s = ds.train_test_split(0.3, shuffle=True, seed=0)
    assert train_s.count() + test_s.count() == 20
    assert sorted(r["x"] for r in train_s.take_all()) != \
        list(range(14))  # shuffled

    with pytest.raises(ValueError):
        ds.split_proportionately([0.7, 0.7])
    with pytest.raises(ValueError):
        ds.train_test_split(1.5)


def test_sample_unique_rename_aggregates(ray_tpu_start):
    """random_sample / unique / rename_columns / column aggregates
    (ref: the same-name Dataset APIs)."""
    ds = rd.from_items(
        [{"x": i, "parity": i % 2} for i in range(100)],
        override_num_blocks=4,
    )
    sampled = ds.random_sample(0.3, seed=0)
    n = sampled.count()
    assert 10 <= n <= 55, n

    assert sorted(ds.unique("parity")) == [0, 1]

    renamed = ds.rename_columns({"x": "value"})
    assert "value" in renamed.columns() or \
        "value" in renamed.take(1)[0]

    assert ds.sum("x") == sum(range(100))
    assert ds.min("x") == 0 and ds.max("x") == 99
    assert abs(ds.mean("x") - 49.5) < 1e-9
    import numpy as _np

    assert abs(ds.std("x") - _np.std(_np.arange(100), ddof=1)) < 1e-6


def test_from_torch(ray_tpu_start):
    """from_torch materializes a map-style torch dataset (ref:
    ray.data.from_torch)."""
    torch = pytest.importorskip("torch")
    from torch.utils.data import TensorDataset

    tds = TensorDataset(torch.arange(12).float()[:, None] * 2)
    ds = rd.from_torch(tds, override_num_blocks=3)
    assert ds.count() == 12
    rows = ds.take_all()
    vals = sorted(float(r["item"][0][0]) for r in rows)
    assert vals == [float(2 * i) for i in range(12)]
