"""ray_tpu.data tests (ref analogue: python/ray/data/tests/)."""

import numpy as np
import pytest

from ray_tpu import data as rd


def test_range_count_take():
    ds = rd.range(100)
    assert ds.count() == 100
    rows = ds.take(5)
    assert [r["id"] for r in rows] == [0, 1, 2, 3, 4]


def test_from_items():
    ds = rd.from_items([{"a": i, "b": i * 2} for i in range(10)])
    assert ds.count() == 10
    assert sorted(r["a"] for r in ds.take_all()) == list(range(10))


def test_map_batches_numpy():
    ds = rd.range(32).map_batches(lambda b: {"x": b["id"] * 2})
    out = ds.to_numpy()
    np.testing.assert_array_equal(np.sort(out["x"]),
                                  np.arange(32, dtype=np.int64) * 2)


def test_map_and_filter_rows():
    ds = rd.range(20).map(lambda r: {"v": int(r["id"]) + 1})
    ds = ds.filter(lambda r: r["v"] % 2 == 0)
    vals = sorted(r["v"] for r in ds.take_all())
    assert vals == [2, 4, 6, 8, 10, 12, 14, 16, 18, 20]


def test_flat_map():
    ds = rd.from_items([{"x": 1}, {"x": 2}]).flat_map(
        lambda r: [{"y": r["x"]}, {"y": r["x"] * 10}]
    )
    assert sorted(r["y"] for r in ds.take_all()) == [1, 2, 10, 20]


def test_iter_batches_sizes():
    ds = rd.range(100, override_num_blocks=7)
    sizes = [len(b["id"]) for b in ds.iter_batches(batch_size=32)]
    assert sum(sizes) == 100
    assert all(s == 32 for s in sizes[:-1])


def test_tensor_columns_roundtrip():
    imgs = np.random.RandomState(0).randint(0, 255, (10, 8, 8, 3),
                                            dtype=np.uint8)
    ds = rd.from_numpy(imgs, column="image")
    out = ds.to_numpy()["image"]
    np.testing.assert_array_equal(np.sort(out.ravel()),
                                  np.sort(imgs.ravel()))
    assert out.shape == (10, 8, 8, 3)


def test_sort_and_limit():
    ds = rd.from_items([{"k": i % 5, "v": i} for i in range(20)])
    s = ds.sort("v", descending=True)
    assert [r["v"] for r in s.take(3)] == [19, 18, 17]
    assert ds.limit(7).count() == 7


def test_random_shuffle_preserves_rows():
    ds = rd.range(50).random_shuffle(seed=42)
    assert sorted(r["id"] for r in ds.take_all()) == list(range(50))


def test_repartition():
    ds = rd.range(30).repartition(3)
    assert ds.num_blocks() == 3
    assert ds.count() == 30


def test_groupby_aggregates():
    ds = rd.from_items([{"k": i % 3, "v": float(i)} for i in range(12)])
    counts = {r["k"]: r["count()"] for r in ds.groupby("k").count().take_all()}
    assert counts == {0: 4, 1: 4, 2: 4}
    sums = {r["k"]: r["sum(v)"] for r in ds.groupby("k").sum("v").take_all()}
    assert sums[0] == 0 + 3 + 6 + 9


def test_streaming_split_shards():
    ds = rd.range(40, override_num_blocks=8)
    shards = ds.streaming_split(4)
    total = sum(s.count() for s in shards)
    assert total == 40
    assert all(s.count() == 10 for s in shards)


def test_csv_parquet_roundtrip(tmp_path):
    import pyarrow as pa
    import pyarrow.parquet as pq

    table = pa.table({"a": list(range(20)), "b": [i * 1.5 for i in range(20)]})
    pq.write_table(table, str(tmp_path / "f1.parquet"))
    pq.write_table(table, str(tmp_path / "f2.parquet"))
    ds = rd.read_parquet(str(tmp_path) + "/*.parquet")
    assert ds.count() == 40
    assert ds.num_blocks() == 2

    import csv

    with open(tmp_path / "data.csv", "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["x", "y"])
        for i in range(10):
            w.writerow([i, i * i])
    ds2 = rd.read_csv(str(tmp_path / "data.csv"))
    assert ds2.count() == 10
    assert ds2.take(3)[2]["y"] == 4


def test_distributed_execution(ray_tpu_start):
    """Blocks execute as remote tasks when the runtime is up."""
    ds = rd.range(64, override_num_blocks=8).map_batches(
        lambda b: {"sq": b["id"] ** 2}
    )
    out = np.sort(ds.to_numpy()["sq"])
    np.testing.assert_array_equal(out, (np.arange(64) ** 2))


def test_iter_jax_batches():
    pytest.importorskip("jax")
    ds = rd.range(32).map_batches(lambda b: {"x": b["id"].astype(np.float32)})
    batches = list(ds.iter_jax_batches(batch_size=16))
    assert len(batches) == 2
    import jax

    assert isinstance(batches[0]["x"], jax.Array)


def test_trainer_dataset_integration(ray_tpu_start, tmp_path):
    """Dataset shards flow into train workers via get_dataset_shard."""
    import ray_tpu
    from ray_tpu import train as rt_train
    from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig

    ds = rd.range(40, override_num_blocks=8)

    def loop():
        shard = rt_train.get_dataset_shard("train")
        n = sum(len(b["id"]) for b in shard.iter_batches(batch_size=10))
        rt_train.report({"rows": n, "rank": rt_train.get_world_rank()})

    result = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(storage_path=str(tmp_path / "di")),
        datasets={"train": ds},
    ).fit()
    assert result.error is None, result.error
    assert result.metrics["rows"] == 20
