"""Borrower/ownership protocol — adversarial reference-counting cases
(ref analogue: python/ray/tests/test_reference_counting_2.py over
src/ray/core_worker/reference_count.h:61: borrower registration, nested
containment pins, borrows outliving tasks, owner death).

These run on a real multi-process cluster with a TIGHT GC (0.5 s grace,
0.1 s delta flush) so any hole in the protocol frees objects that are
still reachable — the old interim pin-while-referenced scheme fails
every cross-node case here.
"""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster

# Test classes pickle by reference and would be unimportable in workers.
import cloudpickle as _cloudpickle
import sys as _sys

_cloudpickle.register_pickle_by_value(_sys.modules[__name__])

TIGHT_GC = {
    "gc_grace_period_s": 0.5,
    "refcount_flush_interval_s": 0.1,
    "log_to_driver": False,
}


def _big():
    # Large enough to live in shared memory (never inlined).
    return np.arange(300_000, dtype=np.float64)


@ray_tpu.remote
class Keeper:
    """Stores whatever container it is handed (refs stay smuggled)."""

    def __init__(self):
        self.box = None

    def stash(self, box):
        self.box = box
        return "stashed"

    def read(self, timeout=20):
        return ray_tpu.get(self.box[0], timeout=timeout)

    def handoff(self, other):
        # Nested borrow: pass the borrowed ref (inside a container) to
        # another actor without the owner's involvement.
        return ray_tpu.get(other.stash.remote(self.box), timeout=30)


@pytest.fixture
def edge_cluster():
    """Head + one worker node carrying resource {edge: 2}."""
    cluster = Cluster(head_resources={"CPU": 2}, system_config=TIGHT_GC)
    cluster.add_node(num_cpus=2, resources={"edge": 2})
    yield cluster
    cluster.shutdown()


def test_smuggled_container_ref_survives_owner_release(edge_cluster):
    """A ref inside a list arg to a REMOTE actor keeps the object alive
    after the driver (owner-side holder) drops its own ref — the remote
    node registers as a borrower with the owner."""
    k = Keeper.options(resources={"edge": 1}).remote()
    ref = ray_tpu.put(_big())
    assert ray_tpu.get(k.stash.remote([ref]), timeout=60) == "stashed"
    del ref
    time.sleep(3.0)  # several GC sweeps at 0.5 s grace
    out = ray_tpu.get(k.read.remote(), timeout=30)
    assert isinstance(out, np.ndarray) and out.shape == (300_000,)


def test_borrowed_ref_outliving_task_then_released(edge_cluster):
    """The borrow ends when the borrower drops the ref: the owner's
    entry must then actually be collected (no leak from the protocol)."""
    k = Keeper.options(resources={"edge": 1}).remote()
    ref = ray_tpu.put(_big())
    oid = ref.id()
    assert ray_tpu.get(k.stash.remote([ref]), timeout=60) == "stashed"
    del ref
    time.sleep(2.0)
    # Borrow still live: readable.
    assert ray_tpu.get(k.read.remote(), timeout=30).shape == (300_000,)
    # Borrower drops its container -> release_borrow -> owner frees.
    assert ray_tpu.get(k.stash.remote([None]), timeout=30) == "stashed"
    from ray_tpu.core.runtime_context import current_runtime

    rt = current_runtime()
    deadline = time.time() + 20
    while time.time() < deadline:
        if not rt._nm.directory.has_entry(oid):
            break
        time.sleep(0.3)
    assert not rt._nm.directory.has_entry(oid), (
        "owner never collected the object after the borrow was released"
    )


def test_nested_ref_inside_put_object(edge_cluster):
    """put([inner_ref]): the containing object pins the inner one
    (AddNestedObjectIds) — dropping the inner ref must not free it while
    the outer object lives, even for a remote borrower."""
    inner = ray_tpu.put(_big())
    outer = ray_tpu.put({"payload": [inner]})
    del inner
    time.sleep(2.5)
    k = Keeper.options(resources={"edge": 1}).remote()
    assert ray_tpu.get(
        k.stash.remote([outer]), timeout=60
    ) == "stashed"

    @ray_tpu.remote(resources={"edge": 1})
    def read_inner(container):
        return ray_tpu.get(container["payload"][0], timeout=20).shape

    assert tuple(
        ray_tpu.get(read_inner.remote(outer), timeout=60)
    ) == (300_000,)


def test_ref_returned_inside_container(edge_cluster):
    """A task that returns [ref] — the return object pins the inner ref
    (reported in the completion frame) until the return itself dies."""

    @ray_tpu.remote(resources={"edge": 1})
    def make_box():
        inner = ray_tpu.put(np.ones(300_000))
        return [inner]  # inner's only live handle rides the return

    box_ref = make_box.remote()
    box = ray_tpu.get(box_ref, timeout=60)
    time.sleep(2.5)  # old scheme: inner's worker ref died with the task
    out = ray_tpu.get(box[0], timeout=30)
    assert float(out.sum()) == 300_000.0


def test_borrow_chain_second_hop(edge_cluster):
    """B borrows from the owner, then hands the ref to C (nested
    borrow). After the owner's holder AND B drop, C must still read."""
    a = Keeper.options(resources={"edge": 1}).remote()
    b = Keeper.options(num_cpus=1).remote()  # head node
    ref = ray_tpu.put(_big())
    assert ray_tpu.get(a.stash.remote([ref]), timeout=60) == "stashed"
    del ref
    # A hands its borrowed container to B.
    assert ray_tpu.get(a.handoff.remote(b), timeout=60) == "stashed"
    # A drops; only B (a second-hop borrower) still holds.
    assert ray_tpu.get(a.stash.remote([None]), timeout=30) == "stashed"
    time.sleep(3.0)
    out = ray_tpu.get(b.read.remote(), timeout=30)
    assert isinstance(out, np.ndarray) and out.shape == (300_000,)


@pytest.mark.slow
def test_borrow_then_owner_node_dies():
    """The owner node dies while a borrow is live: the borrower's read
    must fail CLEANLY (or reconstruct) — never hang (ref analogue:
    OwnerDiedError semantics)."""
    cluster = Cluster(head_resources={"CPU": 2}, system_config=TIGHT_GC)
    owner_node = cluster.add_node(num_cpus=1, resources={"owner": 1})
    cluster.add_node(num_cpus=1, resources={"edge": 1})
    try:
        @ray_tpu.remote(resources={"owner": 1})
        class Producer:
            def make(self):
                return [ray_tpu.put(np.ones(300_000))]

        p = Producer.remote()
        box = ray_tpu.get(p.make.remote(), timeout=60)
        k = Keeper.options(resources={"edge": 1}).remote()
        assert ray_tpu.get(k.stash.remote(box), timeout=60) == "stashed"
        # Kill the owner node (holds the only data copy).
        cluster.remove_node(owner_node)
        time.sleep(2.0)
        t0 = time.monotonic()
        with pytest.raises(Exception):
            ray_tpu.get(k.read.remote(timeout=15), timeout=45)
        assert time.monotonic() - t0 < 60  # failed, not hung
    finally:
        cluster.shutdown()
