"""Mutual TLS on cluster channels (ref: RAY_USE_TLS + tls_utils.py over
gRPC; here core/tls.py over the GCS + peer planes)."""

import os
import subprocess

import numpy as np
import pytest

import ray_tpu


def _make_certs(tmp_path):
    """Self-signed CA + one cluster cert, via the openssl CLI."""
    ca_key = tmp_path / "ca.key"
    ca_crt = tmp_path / "ca.crt"
    key = tmp_path / "node.key"
    csr = tmp_path / "node.csr"
    crt = tmp_path / "node.crt"
    run = lambda *a: subprocess.run(a, check=True, capture_output=True)
    run("openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
        "-keyout", str(ca_key), "-out", str(ca_crt), "-days", "1",
        "-subj", "/CN=rtpu-test-ca")
    run("openssl", "req", "-newkey", "rsa:2048", "-nodes",
        "-keyout", str(key), "-out", str(csr), "-subj", "/CN=rtpu-node")
    run("openssl", "x509", "-req", "-in", str(csr), "-CA", str(ca_crt),
        "-CAkey", str(ca_key), "-CAcreateserial", "-out", str(crt),
        "-days", "1")
    return str(crt), str(key), str(ca_crt)


@pytest.fixture
def tls_env(tmp_path, monkeypatch):
    crt, key, ca = _make_certs(tmp_path)
    # Env overrides reach subprocess nodes/workers too.
    monkeypatch.setenv("RAY_TPU_TLS_CERT_PATH", crt)
    monkeypatch.setenv("RAY_TPU_TLS_KEY_PATH", key)
    monkeypatch.setenv("RAY_TPU_TLS_CA_PATH", ca)
    from ray_tpu.core.config import reset_config

    reset_config()
    yield (crt, key, ca)
    reset_config()


def test_cluster_over_mtls(tls_env, tmp_path):
    """A 2-node cluster (GCS + peer plane + object transfer) runs fully
    over mutual TLS; a client without certs is rejected."""
    from ray_tpu.cluster_utils import Cluster

    c = Cluster(head_resources={"CPU": 2},
                system_config={"log_to_driver": False})
    try:
        c.add_node(num_cpus=1, resources={"gadget": 1})

        @ray_tpu.remote(resources={"gadget": 0.1})
        def produce():
            return np.arange(200_000)  # big enough to cross the peer plane

        assert ray_tpu.get(produce.remote(), timeout=120).sum() == \
            np.arange(200_000).sum()

        # A certless TCP client must be refused by the GCS TLS handshake.
        import socket
        import ssl as _ssl

        host, port = c.gcs_address.split(":")
        raw = socket.create_connection((host, int(port)), timeout=10)
        raw.settimeout(10)
        try:
            plain_ctx = _ssl.SSLContext(_ssl.PROTOCOL_TLS_CLIENT)
            plain_ctx.check_hostname = False
            plain_ctx.verify_mode = _ssl.CERT_NONE
            rejected = False
            try:
                sec = plain_ctx.wrap_socket(raw)  # no client cert
                # TLS 1.3 surfaces the server's certificate_required
                # alert on the first read, possibly as a bare close.
                sec.send(b"x")
                if sec.recv(1) == b"":
                    rejected = True
            except (_ssl.SSLError, ConnectionResetError, OSError):
                rejected = True
            assert rejected, "certless client was accepted"
        finally:
            raw.close()
    finally:
        c.shutdown()
