"""Native C++ arena store: direct module tests + integration through the
core API (cluster-wide zero-copy puts/gets land in the arena).

Ref analogue: the reference's plasma store tests
(src/ray/object_manager/plasma/test/, python/ray/tests/test_object_store.py).
"""

import os

import numpy as np
import pytest

from ray_tpu._native import load_rtstore

rtstore = load_rtstore()

pytestmark = pytest.mark.skipif(
    rtstore is None, reason="native store extension not buildable"
)


def _id(n: int) -> bytes:
    return n.to_bytes(8, "little") + b"\xab" * 12  # 20-byte ObjectID width


@pytest.fixture
def store():
    name = f"/rts-pytest-{os.getpid()}"
    s = rtstore.create(name, 4 << 20)
    yield s
    s.close()
    rtstore.unlink(name)


def test_roundtrip_and_alignment(store):
    v = store.alloc(_id(1), 1000)
    mv = memoryview(v)
    mv[:] = bytes(range(256)) * 3 + bytes(232)
    del mv
    store.seal(_id(1))
    v.release()

    r = store.get(_id(1))
    out = memoryview(r)
    assert bytes(out[:4]) == b"\x00\x01\x02\x03"
    assert r.nbytes == 1000
    arr = np.frombuffer(r, dtype=np.uint8)
    # 64-byte aligned payload for TPU host DMA.
    assert arr.ctypes.data % 64 == 0


def test_missing_and_unsealed(store):
    assert store.get(_id(42)) is None
    store.alloc(_id(2), 64).release()
    assert store.get(_id(2)) is None  # unsealed not readable
    assert not store.contains(_id(2))
    store.seal(_id(2))
    assert store.contains(_id(2))


def test_delete_deferred_by_numpy_view(store):
    v = store.alloc(_id(3), 4096)
    memoryview(v)[:8] = b"pinned!!"
    store.seal(_id(3))
    v.release()

    r = store.get(_id(3))
    arr = np.frombuffer(r, dtype=np.uint8)
    del r  # numpy keeps the View alive through the buffer chain
    store.delete(_id(3))
    assert store.count() == 1  # still pending: arr pins it
    assert arr[:8].tobytes() == b"pinned!!"
    del arr
    assert store.count() == 0
    assert store.used() == 0


def test_full_then_evict(store):
    cap = store.capacity()
    a = store.alloc(_id(4), cap // 2)
    store.seal(_id(4))
    a.release()
    with pytest.raises(MemoryError):
        store.alloc(_id(5), cap - 1024)
    evicted = store.evict(cap, 16)
    assert evicted == [_id(4)]
    v = store.alloc(_id(5), cap // 2)
    store.seal(_id(5))
    v.release()


def test_fragmentation_coalesce(store):
    for i in range(10, 20):
        v = store.alloc(_id(i), 100_000)
        store.seal(_id(i))
        v.release()
    for i in range(10, 20):
        store.delete(_id(i))
    assert store.used() == 0
    # One big allocation must fit again (blocks coalesced).
    v = store.alloc(_id(99), 900_000)
    store.seal(_id(99))
    v.release()


def test_arena_backed_cluster_put_get(tmp_path):
    """End to end: objects above the inline threshold flow through the arena
    in both the driver and worker processes."""
    import ray_tpu
    from ray_tpu.core.object_store import current_arena

    ray_tpu.init()
    try:
        if current_arena() is None:
            pytest.skip("native arena inactive in this session")

        arr = np.arange(200_000, dtype=np.float32)  # 800 KB > inline cap
        ref = ray_tpu.put(arr)
        out = ray_tpu.get(ref)
        np.testing.assert_array_equal(out, arr)

        @ray_tpu.remote
        def double(x):
            return x * 2.0

        out2 = ray_tpu.get(double.remote(ref))
        np.testing.assert_array_equal(out2, arr * 2.0)
        assert current_arena().count() >= 1
    finally:
        ray_tpu.shutdown()
