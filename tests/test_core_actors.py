"""Actor tests (ref analogue: python/ray/tests/test_actor.py)."""

import time

import pytest

import ray_tpu


@ray_tpu.remote
class Counter:
    def __init__(self, start=0):
        self.value = start

    def incr(self, by=1):
        self.value += by
        return self.value

    def read(self):
        return self.value


def test_actor_basic(ray_tpu_start):
    c = Counter.remote()
    assert ray_tpu.get(c.incr.remote()) == 1
    assert ray_tpu.get(c.incr.remote(5)) == 6
    assert ray_tpu.get(c.read.remote()) == 6


def test_actor_constructor_args(ray_tpu_start):
    c = Counter.remote(100)
    assert ray_tpu.get(c.read.remote()) == 100


def test_actor_method_ordering(ray_tpu_start):
    c = Counter.remote()
    refs = [c.incr.remote() for _ in range(20)]
    assert ray_tpu.get(refs) == list(range(1, 21))


def test_two_actors_isolated(ray_tpu_start):
    a, b = Counter.remote(), Counter.remote()
    ray_tpu.get(a.incr.remote())
    assert ray_tpu.get(b.read.remote()) == 0
    assert ray_tpu.get(a.read.remote()) == 1


def test_actor_method_error(ray_tpu_start):
    @ray_tpu.remote
    class Bad:
        def fail(self):
            raise RuntimeError("actor method failed")

        def ok(self):
            return "fine"

    b = Bad.remote()
    with pytest.raises(RuntimeError, match="actor method failed"):
        ray_tpu.get(b.fail.remote())
    # Actor survives a method exception.
    assert ray_tpu.get(b.ok.remote()) == "fine"


def test_actor_constructor_error(ray_tpu_start):
    @ray_tpu.remote
    class Broken:
        def __init__(self):
            raise ValueError("bad init")

        def m(self):
            return 1

    b = Broken.remote()
    with pytest.raises(Exception):
        ray_tpu.get(b.m.remote())


def test_kill_actor(ray_tpu_start):
    c = Counter.remote()
    ray_tpu.get(c.incr.remote())
    ray_tpu.kill(c)
    time.sleep(0.5)
    with pytest.raises(ray_tpu.ActorDiedError):
        ray_tpu.get(c.incr.remote())


def test_named_actor(ray_tpu_start):
    Counter.options(name="global_counter").remote(7)
    time.sleep(0.3)
    handle = ray_tpu.get_actor("global_counter")
    assert ray_tpu.get(handle.read.remote()) == 7


def test_actor_handle_passing(ray_tpu_start):
    c = Counter.remote()

    @ray_tpu.remote
    def bump(counter):
        return ray_tpu.get(counter.incr.remote())

    assert ray_tpu.get(bump.remote(c)) == 1
    assert ray_tpu.get(c.read.remote()) == 1


def test_actor_restart(ray_tpu_start):
    import os

    @ray_tpu.remote(max_restarts=1)
    class Fragile:
        def __init__(self):
            self.count = 0

        def crash(self):
            os._exit(1)

        def ping(self):
            self.count += 1
            return self.count

    f = Fragile.remote()
    assert ray_tpu.get(f.ping.remote()) == 1
    try:
        ray_tpu.get(f.crash.remote())
    except Exception:
        pass
    # After restart, state resets and the actor serves again.
    deadline = time.time() + 10
    while time.time() < deadline:
        try:
            assert ray_tpu.get(f.ping.remote(), timeout=5) >= 1
            break
        except Exception:
            time.sleep(0.2)
    else:
        pytest.fail("actor did not restart")


def test_actor_no_restart_dies(ray_tpu_start):
    import os

    @ray_tpu.remote
    class Fragile:
        def crash(self):
            os._exit(1)

        def ping(self):
            return "pong"

    f = Fragile.remote()
    assert ray_tpu.get(f.ping.remote()) == "pong"
    with pytest.raises(Exception):
        ray_tpu.get(f.crash.remote())
    with pytest.raises(ray_tpu.ActorDiedError):
        ray_tpu.get(f.ping.remote())


def test_async_actor_concurrent_methods(ray_tpu_start):
    """`async def` actor methods run on a per-actor event loop and
    interleave: N concurrent awaits complete in ~1 sleep, not N (ref:
    async actors)."""
    import time

    @ray_tpu.remote
    class AsyncWorker:
        def __init__(self):
            import asyncio

            self.calls = 0
            self.all_in = asyncio.Event()

        async def slow_echo(self, x):
            import asyncio

            # Every coroutine parks until all 8 are in flight — only
            # interleaved execution can complete (event-ordered, no
            # wall-clock sensitivity under load).
            self.calls += 1
            if self.calls == 8:
                self.all_in.set()
            await asyncio.wait_for(self.all_in.wait(), timeout=30)
            return x

        def sync_calls(self):
            return self.calls

    a = AsyncWorker.remote()
    refs = [a.slow_echo.remote(i) for i in range(8)]
    out = ray_tpu.get(refs, timeout=60)
    assert sorted(out) == list(range(8))
    assert ray_tpu.get(a.sync_calls.remote()) == 8
