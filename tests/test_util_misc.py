"""util misc: multiprocessing Pool, ParallelIterator, joblib backend,
check_serialize, distributed tqdm.

Ref analogues: python/ray/util/multiprocessing/pool.py, util/iter.py,
util/joblib/, util/check_serialize.py, experimental/tqdm_ray.py.
"""

import sys as _sys
import threading
import time

import cloudpickle as _cloudpickle
import pytest

_cloudpickle.register_pickle_by_value(_sys.modules[__name__])


def _square(x):
    return x * x


def _add(a, b):
    return a + b


def test_pool_map_and_starmap(ray_tpu_start):
    from ray_tpu.util.multiprocessing import Pool

    with Pool(processes=2) as pool:
        assert pool.map(_square, range(10)) == [x * x for x in range(10)]
        assert pool.map(_square, range(7), chunksize=3) == \
            [x * x for x in range(7)]
        assert pool.starmap(_add, [(1, 2), (3, 4)]) == [3, 7]
        assert pool.apply(_add, (20, 22)) == 42


def test_pool_async_and_imap(ray_tpu_start):
    from ray_tpu.util.multiprocessing import Pool

    with Pool(processes=2) as pool:
        r = pool.map_async(_square, range(6))
        assert r.get(timeout=30) == [0, 1, 4, 9, 16, 25]
        assert r.ready() and r.successful()

        got = list(pool.imap(_square, range(8), chunksize=2))
        assert got == [x * x for x in range(8)]
        unordered = sorted(pool.imap_unordered(_square, range(8),
                                               chunksize=2))
        assert unordered == sorted(x * x for x in range(8))

        # callbacks fire without an explicit get()
        hit = threading.Event()
        pool.apply_async(_add, (1, 1), callback=lambda v: hit.set())
        assert hit.wait(timeout=30)


def test_pool_error_paths(ray_tpu_start):
    from ray_tpu.util.multiprocessing import Pool

    def boom(x):
        raise ValueError("boom")

    pool = Pool(processes=1)
    with pytest.raises(Exception, match="boom"):
        pool.map(boom, [1])
    r = pool.map_async(boom, [1])
    r.wait(timeout=30)
    assert r.ready() and not r.successful()
    pool.close()
    with pytest.raises(ValueError):
        pool.map(_square, [1])
    pool.join()


def test_parallel_iterator(ray_tpu_start):
    from ray_tpu.util import iter as par_iter

    it = (par_iter.from_range(20, num_shards=3)
          .for_each(lambda x: x * 2)
          .filter(lambda x: x % 4 == 0))
    got = sorted(it.gather_sync())
    assert got == sorted(x * 2 for x in range(20) if (x * 2) % 4 == 0)

    # async gather: same multiset, completion order
    got2 = sorted(it.gather_async())
    assert got2 == got

    # batch + flatten round-trip
    b = par_iter.from_items(list(range(10)), num_shards=2).batch(3)
    batches = list(b.gather_sync())
    assert all(isinstance(x, list) for x in batches)
    flat = sorted(par_iter.from_items(list(range(10)), num_shards=2)
                  .batch(3).flatten().gather_sync())
    assert flat == list(range(10))

    # union + take + count
    u = par_iter.from_range(5).union(par_iter.from_range(5))
    assert u.num_shards == 4
    assert u.count() == 10
    assert len(par_iter.from_range(100, num_shards=4).take(7)) == 7

    with pytest.raises(ValueError, match="identical op chains"):
        par_iter.from_range(5).for_each(lambda x: x).union(
            par_iter.from_range(5)
        )


def test_check_serialize():
    from ray_tpu.util.check_serialize import inspect_serializability

    ok, failures = inspect_serializability(lambda x: x + 1,
                                           print_report=False)
    assert ok and not failures

    lock = threading.Lock()

    def uses_lock():
        return lock

    ok, failures = inspect_serializability(uses_lock,
                                           print_report=False)
    assert not ok
    assert any(f.name == "lock" for f in failures), failures


def test_joblib_backend(ray_tpu_start):
    joblib = pytest.importorskip("joblib")
    from ray_tpu.util.joblib import register_ray

    register_ray()
    with joblib.parallel_backend("ray_tpu", n_jobs=2):
        out = joblib.Parallel()(
            joblib.delayed(_square)(i) for i in range(12)
        )
    assert out == [i * i for i in range(12)]


def test_tqdm_distributed(ray_tpu_start):
    """Worker-side tqdm proxies publish progress the driver renderer
    aggregates (rendering disabled: state only)."""
    import ray_tpu
    from ray_tpu.util.tqdm import driver_progress

    @ray_tpu.remote
    def work(k):
        from ray_tpu.util.tqdm import tqdm

        total = 0
        for x in tqdm(range(50), desc=f"job-{k}",
                      flush_interval_s=0.0):
            total += x
        return total

    with driver_progress(render=False) as renderer:
        out = ray_tpu.get([work.remote(i) for i in range(2)])
        assert out == [sum(range(50))] * 2
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            done = [s for s in renderer.state.values()
                    if s["closed"] and s["n"] == 50]
            if len(done) >= 2:
                break
            time.sleep(0.2)
        closed = [s for s in renderer.state.values() if s["closed"]]
        assert len(closed) >= 2, renderer.state
        assert all(s["total"] == 50 for s in closed)


def test_usage_stats_local_report(ray_tpu_start, tmp_path, monkeypatch):
    """Usage report: libraries recorded, written locally, opt-out
    honored (ref: usage_lib — local-only here, zero egress)."""
    from ray_tpu.util import usage_stats

    import ray_tpu.data  # noqa: F401 - records "data"

    report = usage_stats.build_report()
    assert "data" in report["libraries_used"]
    assert report["ray_tpu_version"]
    assert report.get("num_nodes", 0) >= 1

    path = usage_stats.write_report(str(tmp_path))
    assert path
    import json as _json

    with open(path) as f:
        on_disk = _json.load(f)
    assert on_disk["schema_version"] == "0.1"

    monkeypatch.setenv("RAY_TPU_USAGE_STATS_ENABLED", "0")
    assert usage_stats.write_report(str(tmp_path / "off")) == ""
    assert not (tmp_path / "off" / "usage_stats.json").exists()
