"""Typed RPC service layer + general pubsub channels.

Ref analogues: src/ray/rpc/grpc_server.h (typed service dispatch),
src/ray/protobuf/gcs_service.proto (schemas), src/ray/pubsub/publisher.h
(per-subscriber long-poll queues), python/ray/_private/gcs_pubsub.py
(driver-side subscriber).
"""

import asyncio
import threading
import time

import pytest


# ---------------------------------------------------------------- rpc unit


def _echo_service():
    from ray_tpu.core.rpc import Method, ServiceSpec

    spec = ServiceSpec("EchoService", (
        Method("echo", request=(("text", "str"), ("times", "int", False, 1)),
               reply=(("out", "str"),)),
        Method("fire", request=(("text", "str"),), notify=True),
    ))

    class Impl:
        def __init__(self):
            self.fired = []

        async def _rpc_echo(self, ctx, text, times=1):
            return {"out": text * times, "ctx": ctx}

        async def _rpc_fire(self, ctx, text):
            self.fired.append(text)

    return spec, Impl()


def test_registry_validates_and_dispatches():
    from ray_tpu.core.rpc import RpcError, ServiceRegistry

    spec, impl = _echo_service()
    reg = ServiceRegistry()
    reg.register(spec, impl)

    async def run():
        out = await reg.dispatch("node-1", "echo",
                                 {"text": "ab", "times": 2})
        assert out["out"] == "abab" and out["ctx"] == "node-1"
        # optional field defaults
        out = await reg.dispatch(None, "echo", {"text": "x"})
        assert out["out"] == "x"
        # notify returns None and side-effects
        assert await reg.dispatch(None, "fire", {"text": "t"}) is None
        assert impl.fired == ["t"]
        # unknown op
        with pytest.raises(RpcError, match="unknown rpc method"):
            await reg.dispatch(None, "nope", {})
        # missing required field
        with pytest.raises(RpcError, match="missing required"):
            await reg.dispatch(None, "echo", {"times": 2})
        # wrong type
        with pytest.raises(RpcError, match="expects str"):
            await reg.dispatch(None, "echo", {"text": 7})

    asyncio.run(run())


def test_stub_and_describe():
    from ray_tpu.core.rpc import RpcError, ServiceStub

    spec, _ = _echo_service()

    class FakeTransport:
        def __init__(self):
            self.sent = []

        async def request(self, msg, timeout=30.0):
            self.sent.append(("req", msg))
            return {"ok": True, "msg": msg}

        async def notify(self, msg):
            self.sent.append(("ntf", msg))

    t = FakeTransport()
    stub = ServiceStub(spec, t)

    async def run():
        r = await stub.echo(text="hi", times=3)
        assert r["msg"] == {"op": "echo", "text": "hi", "times": 3}
        await stub.fire(text="bang")
        assert t.sent[-1][0] == "ntf"
        # client-side validation: before the wire
        with pytest.raises(RpcError, match="missing required"):
            await stub.echo(times=1)
        with pytest.raises(RpcError, match="unknown fields"):
            await stub.echo(text="x", bogus=1)

    asyncio.run(run())

    from ray_tpu.core.rpc import ServiceRegistry

    spec2, impl = _echo_service()
    reg = ServiceRegistry()
    reg.register(spec2, impl)
    desc = reg.describe()
    assert "EchoService" in desc
    assert desc["EchoService"]["echo"]["request"][0]["name"] == "text"
    assert desc["EchoService"]["fire"]["notify"] is True


def test_gcs_service_schemas_cover_dispatch():
    """Every GCS op reachable over the wire has a schema entry, and the
    registry builds cleanly against the GcsService implementation."""
    from ray_tpu.core.gcs import GCS_SERVICES, GcsService

    ops = [m.name for spec in GCS_SERVICES for m in spec.methods]
    assert len(ops) == len(set(ops))
    for op in ("register_node", "heartbeat", "kv_put", "kv_get",
               "register_named_actor", "locate_object", "pg_create",
               "psub_poll", "rpc_describe"):
        assert op in ops
    for spec in GCS_SERVICES:
        for m in spec.methods:
            assert callable(getattr(GcsService, m.handler, None)), \
                f"GcsService missing handler {m.handler} for {m.name}"


# ------------------------------------------------------------- pubsub unit


def test_publisher_fanout_and_drops():
    from ray_tpu.core.pubsub import Publisher

    async def run():
        pub = Publisher(max_queue=3)
        pub.subscribe("a", ["c1"])
        pub.subscribe("b", ["c1", "c2"])
        pub.publish("c1", {"v": 1})
        pub.publish("c2", {"v": 2})
        ra = await pub.poll("a", timeout=0.01)
        rb = await pub.poll("b", timeout=0.01)
        assert [e["data"]["v"] for e in ra["events"]] == [1]
        assert [e["data"]["v"] for e in rb["events"]] == [1, 2]
        # seq increases; key rides along
        seq = pub.publish("c1", "x", key="k")
        assert seq > 0
        ev = (await pub.poll("a", timeout=0.01))["events"][0]
        assert ev["key"] == "k" and ev["seq"] == seq
        # unknown subscriber is flagged, not an error
        assert (await pub.poll("zz", timeout=0.01))["unknown"]
        # bounded queue: oldest dropped, drop counted
        for i in range(5):
            pub.publish("c1", i)
        ra = await pub.poll("a", timeout=0.01)
        assert ra["dropped"] == 2
        assert [e["data"] for e in ra["events"]] == [2, 3, 4]
        # unsubscribe stops delivery
        pub.unsubscribe("a")
        pub.publish("c1", "gone")
        assert (await pub.poll("a", timeout=0.01))["unknown"]

    asyncio.run(run())


def test_publisher_longpoll_wakes():
    from ray_tpu.core.pubsub import Publisher

    async def run():
        pub = Publisher()
        pub.subscribe("s", ["ch"])

        async def later():
            await asyncio.sleep(0.05)
            pub.publish("ch", "wake")

        asyncio.ensure_future(later())
        t0 = time.monotonic()
        r = await pub.poll("s", timeout=5.0)
        assert [e["data"] for e in r["events"]] == ["wake"]
        assert time.monotonic() - t0 < 2.0  # woke on publish, not timeout

    asyncio.run(run())


# ------------------------------------------------------------ integration


def test_pubsub_end_to_end(ray_tpu_start):
    """Driver subscriber sees control-plane events (named actor
    registration on actor_state) and user publishes."""
    import ray_tpu
    from ray_tpu.util.pubsub import ACTOR_STATE, Subscriber, publish

    with Subscriber(channels=[ACTOR_STATE, "user_events"]) as sub:
        @ray_tpu.remote
        class A:
            def ping(self):
                return "pong"

        a = A.options(name="pubsub_probe").remote()
        assert ray_tpu.get(a.ping.remote()) == "pong"

        events = []
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            events.extend(sub.poll(timeout=1.0))
            if any(e["channel"] == ACTOR_STATE and
                   e["data"].get("name") == "pubsub_probe"
                   for e in events):
                break
        reg = [e for e in events
               if e["data"].get("name") == "pubsub_probe"]
        assert reg and reg[0]["data"]["event"] == \
            "named_actor_registered"

        seq = publish("user_events", {"hello": "world"})
        assert seq > 0
        got = []
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            got.extend(e for e in sub.poll(timeout=1.0)
                       if e["channel"] == "user_events")
            if got:
                break
        assert got[0]["data"] == {"hello": "world"}
        ray_tpu.kill(a)


def test_describe_services_end_to_end(ray_tpu_start):
    """rpc_describe exposes the typed GCS surface to clients."""
    from ray_tpu.util.pubsub import describe_services

    services = describe_services()
    assert "InternalKVService" in services
    assert "InternalPubSubService" in services
    kv_put = services["InternalKVService"]["kv_put"]
    assert {f["name"] for f in kv_put["request"]} == \
        {"key", "value", "overwrite"}


def test_node_lifecycle_events():
    """node_state channel carries added + dead events across a real
    multi-node cluster (ref: node state pubsub feeding dashboards)."""
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.util.pubsub import NODE_STATE, Subscriber

    c = Cluster(
        head_resources={"CPU": 1},
        system_config={"num_prestart_workers": 0,
                       "node_death_timeout_s": 3.0},
    )
    try:
        with Subscriber(channels=[NODE_STATE]) as sub:
            handle = c.add_node(resources={"CPU": 1})
            events = []
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline:
                events.extend(sub.poll(timeout=1.0))
                if any(e["data"]["event"] == "added" for e in events):
                    break
            added = [e for e in events if e["data"]["event"] == "added"]
            assert added, events

            c.remove_node(handle)
            deadline = time.monotonic() + 20
            while time.monotonic() < deadline:
                events.extend(sub.poll(timeout=1.0))
                if any(e["data"]["event"] == "dead" for e in events):
                    break
            dead = [e for e in events if e["data"]["event"] == "dead"]
            assert dead, events
            assert dead[0]["key"] == dead[0]["data"]["node_id"]
    finally:
        c.shutdown()


def test_worker_can_publish_and_subscribe(ray_tpu_start):
    """Pubsub works from task workers too (the proxy rides the
    worker<->node channel)."""
    import ray_tpu
    from ray_tpu.util.pubsub import Subscriber, publish

    @ray_tpu.remote
    def announce():
        from ray_tpu.util.pubsub import publish as wpub

        return wpub("worker_ch", {"from": "worker"})

    with Subscriber(channels=["worker_ch"]) as sub:
        seq = ray_tpu.get(announce.remote())
        assert seq > 0
        events = []
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            events.extend(sub.poll(timeout=1.0))
            if events:
                break
        assert events and events[0]["data"] == {"from": "worker"}
