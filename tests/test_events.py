"""Cluster event log + failure-history plane (ref analogue: the state
API's cluster-event tests + task-event buffer retention tests):
emission → pubsub → aggregator ordering, ring-buffer bounds, terminal
task retention, severity/source filters, JSONL sink round-trip, and the
state-API satellites."""

import json
import os
import time
import uuid

import pytest

import ray_tpu
from ray_tpu.util import events
from ray_tpu.util import state as state_api


def _poll(fn, timeout=12.0, interval=0.2):
    """Poll fn() until truthy (events flush on a 0.25s cadence and hop
    through the pubsub aggregator)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        out = fn()
        if out:
            return out
        time.sleep(interval)
    return fn()


# ------------------------------------------------------- event primitives


def test_event_buffer_bounds_and_drop_counting():
    """Ring buffer keeps the NEWEST maxlen events and counts drops."""
    buf = events.EventBuffer(maxlen=3)
    for i in range(5):
        buf.append(events.make_event(events.INFO, events.TASK, f"m{i}"))
    assert len(buf) == 3
    batch, dropped = buf.drain()
    assert dropped == 2
    assert [e["message"] for e in batch] == ["m2", "m3", "m4"]
    # Drain resets both the buffer and the drop counter.
    assert buf.drain() == ([], 0)


def test_make_event_validates_enums():
    """Unknown severity/source raise (the lint checks the same enums
    statically at emit sites)."""
    with pytest.raises(ValueError, match="severity"):
        events.make_event("LOUD", events.TASK, "x")
    with pytest.raises(ValueError, match="source"):
        events.make_event(events.INFO, "KERNEL", "x")
    e = events.make_event(events.WARNING, events.SERVE, "ok",
                          custom_fields={"k": 1})
    assert e["severity"] == "WARNING" and e["source"] == "SERVE"
    assert e["custom_fields"] == {"k": 1} and e["event_id"]


def test_event_store_bounded_and_severity_indexed():
    store = events.EventStore(maxlen=5)
    for i in range(8):
        sev = events.ERROR if i % 2 else events.INFO
        store.add(events.make_event(sev, events.GCS, f"m{i}"))
    msgs = [e["message"] for e in store.list()]
    assert msgs == [f"m{i}" for i in range(3, 8)]  # oldest aged out
    errs = [e["message"] for e in store.list(severity=events.ERROR)]
    assert errs == ["m1", "m3", "m5", "m7"]  # index keeps its own window
    assert store.stats()["total"] == 8
    assert [e["message"] for e in store.list(limit=2)] == ["m6", "m7"]


def test_event_store_jsonl_sink_round_trip(tmp_path):
    """Every aggregated event lands in the JSONL export sink and parses
    back with its fields intact."""
    path = str(tmp_path / "exports" / "events.jsonl")
    store = events.EventStore(maxlen=100, jsonl_path=path)
    sent = [
        events.make_event(events.INFO, events.GCS, "a"),
        events.make_event(events.ERROR, events.TASK, "b",
                          task_id="t1", custom_fields={"error_type": "X"}),
        events.make_event(events.WARNING, events.AUTOSCALER, "c"),
    ]
    for e in sent:
        store.add(e)
    store.close()
    with open(path) as f:
        lines = [json.loads(line) for line in f]
    assert [e["message"] for e in lines] == ["a", "b", "c"]
    assert lines[1]["severity"] == "ERROR"
    assert lines[1]["task_id"] == "t1"
    assert lines[1]["custom_fields"] == {"error_type": "X"}
    assert [e["event_id"] for e in lines] == [e["event_id"] for e in sent]


# --------------------------------------------------- end-to-end pipeline


def test_emission_pubsub_aggregator_ordering(ray_tpu_start):
    """Events emitted in order arrive at the head store in order (the
    pubsub seq is the store order)."""
    marker = uuid.uuid4().hex[:8]
    for i in range(5):
        events.emit(events.INFO, events.JOB, f"ordered-{marker}-{i}")
    events.flush()

    def got():
        evs = [e for e in state_api.list_cluster_events(source="JOB")
               if marker in e["message"]]
        return evs if len(evs) == 5 else None

    evs = _poll(got)
    assert [e["message"].rsplit("-", 1)[1] for e in evs] == \
        [str(i) for i in range(5)]
    seqs = [e["seq"] for e in evs]
    assert seqs == sorted(seqs)
    # The emitting process's node id was stamped on.
    assert all(e["node_id"] for e in evs)


def test_list_cluster_events_severity_source_filters(ray_tpu_start):
    marker = uuid.uuid4().hex[:8]
    events.emit(events.ERROR, events.JOB, f"f-{marker}-err")
    events.emit(events.INFO, events.JOB, f"f-{marker}-info")
    events.flush()
    _poll(lambda: len([e for e in state_api.list_cluster_events(
        source="JOB") if marker in e["message"]]) == 2 or None)
    errs = [e for e in state_api.list_cluster_events(severity="ERROR")
            if marker in e["message"]]
    assert [e["message"] for e in errs] == [f"f-{marker}-err"]
    # Generic (key, pred, value) filters compose on top.
    infos = [e for e in state_api.list_cluster_events(
        source="JOB", filters=[("severity", "!=", "ERROR")])
        if marker in e["message"]]
    assert [e["message"] for e in infos] == [f"f-{marker}-info"]
    with pytest.raises(ValueError):
        state_api.list_cluster_events(filters=[("severity", ">", "X")])


def test_failed_task_retained_with_error_and_event(ray_tpu_start):
    """Acceptance: a deliberately failing task yields (1) a retained
    list_tasks row with error type/message after the live record is
    gone, (2) a severity-ERROR cluster event, (3) failed counts +
    per-function duration stats in summarize_tasks."""

    @ray_tpu.remote
    def boom():
        raise ValueError("kaboom-xyz")

    @ray_tpu.remote
    def fine():
        return 1

    assert ray_tpu.get(fine.remote(), timeout=30) == 1
    with pytest.raises(Exception, match="kaboom-xyz"):
        ray_tpu.get(boom.remote(), timeout=30)

    row = _poll(lambda: next(
        (t for t in state_api.list_tasks()
         if t.get("retained") and t["state"] == "failed"
         and t["name"] == "boom"), None))
    assert row["error_type"] == "ValueError", row
    assert "kaboom-xyz" in row["error_message"], row
    assert row["duration_s"] is not None
    # The live table no longer carries it; only the retained row does.
    live = [t for t in state_api.list_tasks()
            if t["name"] == "boom" and not t.get("retained")]
    assert not live

    ev = _poll(lambda: next(
        (e for e in state_api.list_cluster_events(severity="ERROR")
         if e["source"] == "TASK" and "boom" in e["message"]), None))
    assert "ValueError" in ev["message"]
    assert ev["task_id"] == row["task_id"]
    assert "traceback" in ev["custom_fields"]  # provenance travels along

    summ = state_api.summarize_tasks()
    assert summ["failed"] >= 1
    assert summ["by_state"]["failed"] >= 1
    f = summ["per_func"]["boom"]
    assert f["count"] == 1 and f["failed"] == 1
    assert f["mean_duration_s"] is not None
    assert summ["per_func"]["fine"]["failed"] == 0


def test_killed_worker_crash_event_and_history(ray_tpu_start):
    """Acceptance: a killed worker produces a severity-ERROR WORKER
    event carrying the exit code, and the interrupted task is retained
    as failed with WorkerCrashedError."""

    @ray_tpu.remote(max_retries=0)
    def die():
        os._exit(17)

    with pytest.raises(Exception):
        ray_tpu.get(die.remote(), timeout=30)

    row = _poll(lambda: next(
        (t for t in state_api.list_tasks()
         if t.get("retained") and t["name"] == "die"), None))
    assert row["state"] == "failed"
    assert row["error_type"] == "WorkerCrashedError", row
    assert row["retries_left"] == 0 and row["retry_count"] == 0, row

    wev = _poll(lambda: next(
        (e for e in state_api.list_cluster_events(severity="ERROR")
         if e["source"] == "WORKER" and "crashed" in e["message"]), None))
    # The event must exist and carry the exit classification; the exact
    # numeric code is racy (the reaper can observe the direct os._exit
    # code OR a signal-class negative code depending on who wins the
    # wait), so assert on presence + class, not the literal value.
    assert wev is not None, "no WORKER crash event"
    ec = wev["custom_fields"].get("exit_code")
    assert ec is not None and isinstance(ec, int), wev
    assert ec == 17 or ec < 0, wev  # direct code or signal-class exit
    tev = next(
        (e for e in state_api.list_cluster_events(severity="ERROR")
         if e["source"] == "TASK" and "die" in e["message"]), None)
    assert tev is not None


def test_dashboard_events_route(ray_tpu_start):
    """/api/events serves the aggregated store with query filters."""
    import urllib.request

    from ray_tpu import dashboard

    marker = uuid.uuid4().hex[:8]
    events.emit(events.ERROR, events.JOB, f"dash-{marker}")
    events.flush()
    _poll(lambda: [e for e in state_api.list_cluster_events(source="JOB")
                   if marker in e["message"]] or None)
    port = dashboard.start_dashboard(port=0)
    try:
        def fetch(path):
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{path}", timeout=30) as r:
                return json.loads(r.read())

        evs = fetch("/api/events?severity=ERROR&source=JOB")["events"]
        assert any(marker in e["message"] for e in evs), evs
        assert all(e["severity"] == "ERROR" for e in evs)
        assert fetch("/api/events?limit=1")["events"]
    finally:
        dashboard.stop_dashboard()


# ------------------------------------------------------ state satellites


def test_list_nodes_rejects_unknown_predicate(ray_tpu_start):
    """list_nodes now matches _query: unsupported predicates raise
    instead of silently returning unfiltered rows."""
    assert state_api.list_nodes(filters=[("Alive", "=", True)])
    with pytest.raises(ValueError, match="predicate"):
        state_api.list_nodes(filters=[("Alive", ">", 0)])


def test_list_placement_groups_accepts_filters(ray_tpu_start):
    from ray_tpu.util import placement_group, remove_placement_group

    pg = placement_group([{"CPU": 1}], strategy="PACK")
    assert pg.wait(timeout_seconds=10)
    try:
        rows = state_api.list_placement_groups()
        assert rows
        created = state_api.list_placement_groups(
            filters=[("state", "=", "created")]
        )
        assert created
        assert state_api.list_placement_groups(
            filters=[("state", "=", "no_such_state")]
        ) == []
        with pytest.raises(ValueError):
            state_api.list_placement_groups(filters=[("state", "~", "x")])
    finally:
        remove_placement_group(pg)


def test_summarize_objects_tolerates_missing_sizes(ray_tpu_start,
                                                   monkeypatch):
    """In-flight/spilled rows with size_bytes=None count as 0 instead of
    raising TypeError."""
    rows = [
        {"object_id": "a", "size_bytes": 10, "where": "inline"},
        {"object_id": "b", "size_bytes": None, "where": "spilled"},
        {"object_id": "c", "where": "remote"},  # key absent entirely
    ]
    monkeypatch.setattr(state_api, "list_objects", lambda: rows)
    out = state_api.summarize_objects()
    assert out["total_objects"] == 3
    assert out["total_size_bytes"] == 10
    assert out["by_location"] == {"inline": 1, "spilled": 1, "remote": 1}


def test_log_monitor_caches_pid_lookup(tmp_path):
    """_pid_for resolves via the worker table once, then serves the
    cached pid (the rescan was O(files x workers) every 200 ms)."""
    from ray_tpu.core.ids import WorkerID
    from ray_tpu.core.log_monitor import LogMonitor

    class _Proc:
        pid = 4242

    class _Handle:
        proc = _Proc()

    class _NodeID:
        @staticmethod
        def hex():
            return "ab" * 16

    wid = WorkerID.from_random()

    class _NM:
        node_id = _NodeID()
        _workers = {wid: _Handle()}

    mon = LogMonitor(str(tmp_path), node_manager=_NM())
    path = os.path.join(str(tmp_path), "logs",
                        f"worker-{wid.hex()[:8]}.log")
    assert mon._pid_for(path) == "4242"
    # Worker left the table (exited): the resolved pid must survive.
    _NM._workers.clear()
    assert mon._pid_for(path) == "4242"
    # An unknown file stays unresolved (and uncached).
    other = os.path.join(str(tmp_path), "logs", "worker-deadbeef.log")
    assert mon._pid_for(other) == "?"
