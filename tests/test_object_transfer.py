"""Chunked inter-node object transfer (ref analogue: the object manager's
chunked Push/Pull — object_manager.proto:61, 5 MiB chunks per
object_manager_default_chunk_size, pull_manager.h admission). Chunk size
is shrunk via system_config so modest arrays exercise the multi-chunk
path."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster

CHUNK = 256 * 1024  # 256 KiB chunks force multi-chunk transfers


@pytest.fixture
def cluster():
    c = Cluster(
        head_resources={"CPU": 2},
        system_config={
            "num_prestart_workers": 1,
            "default_max_retries": 0,
            "object_transfer_chunk_bytes": CHUNK,
            "pull_chunks_in_flight": 3,
        },
    )
    yield c
    c.shutdown()


def test_chunked_pull_roundtrip(cluster):
    """A multi-chunk object produced on a remote node reads back intact
    (content hash verified end to end)."""
    cluster.add_node(num_cpus=1, resources={"gadget": 1})

    @ray_tpu.remote(resources={"gadget": 1})
    def produce():
        rng = np.random.RandomState(7)
        return rng.randint(0, 255, size=CHUNK * 3 + 12345, dtype=np.uint8)

    got = ray_tpu.get(produce.remote(), timeout=120)
    rng = np.random.RandomState(7)
    expected = rng.randint(0, 255, size=CHUNK * 3 + 12345, dtype=np.uint8)
    assert got.shape == expected.shape
    assert np.array_equal(got, expected)
    # The transfer really took the large-object path: striped over the
    # data plane (default) or >= 4 control-plane chunks (fallback).
    from ray_tpu.core.runtime_context import current_runtime

    stats = current_runtime()._nm._transfer.stats
    assert stats["chunked_pulls"] >= 1, stats
    assert (stats["striped_pulls"] >= 1
            and stats["bytes_pulled_stream"] >= CHUNK * 3) \
        or stats["chunks_pulled"] >= 4, stats


def test_chunked_broadcast_to_multiple_nodes(cluster):
    """Broadcast: several nodes pull the same large object from one
    source concurrently (ref: the 1 GiB broadcast envelope line —
    release/benchmarks/README.md:17)."""
    cluster.add_node(num_cpus=1, resources={"gadget": 1})
    cluster.add_node(num_cpus=1, resources={"widgetA": 1})
    cluster.add_node(num_cpus=1, resources={"widgetB": 1})

    @ray_tpu.remote(resources={"gadget": 1})
    def produce():
        return np.arange(CHUNK // 8 * 5, dtype=np.int64)  # ~5 chunks

    ref = produce.remote()

    @ray_tpu.remote(resources={"widgetA": 1})
    def check_a(arr):
        return int(arr.sum())

    @ray_tpu.remote(resources={"widgetB": 1})
    def check_b(arr):
        return int(arr.sum())

    n = CHUNK // 8 * 5
    expected = n * (n - 1) // 2
    sums = ray_tpu.get(
        [check_a.remote(ref), check_b.remote(ref)], timeout=120
    )
    assert sums == [expected, expected]


def test_concurrent_rpcs_survive_large_transfer(cluster):
    """Control-plane traffic (small actor calls) keeps flowing while a
    multi-chunk transfer is in progress — the peer socket is never held
    by one giant frame (VERDICT r2 missing #2)."""
    import time

    cluster.add_node(num_cpus=2, resources={"gadget": 2})

    @ray_tpu.remote(resources={"gadget": 1})
    class Pinger:
        def ping(self):
            return "pong"

    @ray_tpu.remote(resources={"gadget": 1})
    def produce():
        return np.zeros(CHUNK * 8 // 8, dtype=np.int64)  # 8 chunks

    p = Pinger.remote()
    assert ray_tpu.get(p.ping.remote(), timeout=60) == "pong"
    big_ref = produce.remote()
    # Start the pull by getting the big object while pinging concurrently.
    import threading

    pings = []

    def ping_loop():
        for _ in range(10):
            pings.append(ray_tpu.get(p.ping.remote(), timeout=60))
            time.sleep(0.01)

    t = threading.Thread(target=ping_loop)
    t.start()
    big = ray_tpu.get(big_ref, timeout=120)
    t.join(timeout=60)
    assert big.nbytes == CHUNK * 8
    assert pings == ["pong"] * 10


def test_pull_admission_queues_on_memory():
    """Concurrent pulls whose combined size exceeds the (shrunk) store
    are admitted one at a time instead of blowing shm allocation
    (VERDICT r3 ask #6; ref: pull_manager.h:52)."""
    import numpy as np

    from ray_tpu.cluster_utils import Cluster

    c = Cluster(
        head_resources={"CPU": 2},
        system_config={
            "log_to_driver": False,
            "object_store_memory": 64 * 1024 * 1024,
            "object_spilling_enabled": True,
        },
    )
    try:
        c.add_node(num_cpus=2, resources={"gadget": 1})

        @ray_tpu.remote(resources={"gadget": 0.1})
        def produce(i):
            return np.full(30 * 1024 * 1024 // 8, i, dtype=np.int64)

        refs = [produce.remote(i) for i in range(3)]
        vals = ray_tpu.get(refs, timeout=300)  # 90 MB through a 64 MB store
        for i, v in enumerate(vals):
            assert v[0] == i and v.nbytes == 30 * 1024 * 1024
        from ray_tpu.core import runtime_context

        stats = runtime_context.current_runtime()._nm._transfer.stats
        assert stats["chunked_pulls"] >= 3
    finally:
        c.shutdown()


def test_pull_larger_than_store_fails_cleanly():
    """A single object bigger than the whole store raises a clean error
    instead of crashing shm allocation mid-transfer."""
    import numpy as np

    import pytest as _pytest

    from ray_tpu.cluster_utils import Cluster

    c = Cluster(
        head_resources={"CPU": 2},
        system_config={
            "log_to_driver": False,
            "object_store_memory": 16 * 1024 * 1024,
            "pull_admission_timeout_s": 5.0,
        },
    )
    try:
        c.add_node(num_cpus=2, resources={"gadget": 1})

        @ray_tpu.remote(resources={"gadget": 0.1})
        def produce_big():
            return np.zeros(32 * 1024 * 1024 // 8, dtype=np.int64)

        with _pytest.raises(Exception) as ei:
            ray_tpu.get(produce_big.remote(), timeout=120)
        msg = str(ei.value)
        assert "exceeds the object store capacity" in msg or \
            "lost" in msg.lower() or "not admitted" in msg, msg
    finally:
        c.shutdown()
