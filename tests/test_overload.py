"""Request-robustness / overload-control tests.

The acceptance story (ISSUE 7): with chaos-armed latency injection on
one replica of a 2-replica deployment under sustained load, the sick
replica's circuit breaker opens and traffic shifts (goodput of
in-deadline requests >= 95%), deadline-expired requests are provably
never executed replica-side, the proxy sheds with 503 + Retry-After
instead of queueing unboundedly, and half-open probes re-admit the
replica after heal.
"""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.core.exceptions import DeadlineExceededError, OverloadedError
from ray_tpu.util import faults, overload

# ------------------------------------------------------ primitive units


def test_aimd_limiter_adapts():
    t = [0.0]
    lim = overload.AIMDLimiter(
        initial=4, min_limit=1, max_limit=8, latency_target_s=0.5,
        decrease_interval_s=0.0, clock=lambda: t[0],
    )
    assert lim.limit == 4
    # Steady latency ABOVE the absolute target is this service's
    # normal (a 3s TPU forward pass): the baseline learns it and the
    # limit still grows — slow-but-healthy must not collapse to min.
    for _ in range(20):
        if lim.try_acquire():
            t[0] += 0.1
            lim.release(1.0)
    assert lim.limit >= 4
    # DEGRADATION vs the service's own baseline shrinks
    # multiplicatively (queueing inflates latency well past 2x).
    for _ in range(6):
        if lim.try_acquire():
            t[0] += 0.1
            lim.release(5.0)
    assert lim.limit < 4
    floor = lim.limit
    # ...recovery grows back additively (bounded by max).
    for _ in range(200):
        if lim.try_acquire():
            lim.release(1.0)
    assert lim.limit > floor
    assert lim.limit <= 8
    # An explicit overload signal decreases without any latency sample.
    before = lim.limit
    lim.on_reject()
    assert lim.limit < before or lim.limit == 1
    # Saturation sheds.
    lim2 = overload.AIMDLimiter(initial=1, max_limit=1)
    assert lim2.try_acquire()
    assert not lim2.try_acquire()
    assert lim2.sheds == 1


def test_admission_gate_sheds_full_queue_and_evicts_by_age():
    gate = overload.AdmissionGate(
        overload.AIMDLimiter(initial=1, max_limit=1), max_queue=0
    )
    gate.acquire()  # takes the only slot
    # Queue bound 0: the next request sheds immediately, pre-queue.
    with pytest.raises(OverloadedError) as ei:
        gate.acquire()
    assert ei.value.retry_after_s > 0
    assert gate.shed_full == 1
    gate.release(0.01)

    # Queue bound 1: the request queues, then is EVICTED BY AGE the
    # moment its deadline passes (age-based eviction behind the gate).
    gate2 = overload.AdmissionGate(
        overload.AIMDLimiter(initial=1, max_limit=1), max_queue=1
    )
    gate2.acquire()
    t0 = time.monotonic()
    with pytest.raises(OverloadedError):
        gate2.acquire(deadline_ts=time.time() + 0.15)
    assert 0.1 <= time.monotonic() - t0 < 2.0
    assert gate2.shed_expired == 1


def test_circuit_breaker_open_probe_close_cycle():
    t = [0.0]
    transitions = []
    br = overload.CircuitBreaker(
        error_threshold=0.5, min_volume=4, open_base_s=1.0,
        clock=lambda: t[0], seed=7, on_transition=transitions.append,
    )
    assert br.allow()
    for _ in range(4):
        br.record(False)
    assert br.state == "open"
    assert not br.allow()
    assert br.opens == 1
    assert not br.probe_due()  # backoff window still running
    t[0] += 2.0  # past base delay (+25% jitter bound)
    assert br.probe_due()
    br.begin_probe()
    assert br.state == "half_open"
    assert not br.probe_due()  # probe claimed, not yet timed out
    br.record(False)  # failed probe -> back open, longer delay
    assert br.state == "open"
    t[0] += 4.0
    assert br.probe_due()
    br.begin_probe()
    br.record(True)  # successful probe -> closed, window cleared
    assert br.state == "closed"
    assert br.allow()
    assert transitions[0] == "open" and transitions[-1] == "closed"


def test_retry_budget_caps_amplification():
    b = overload.RetryBudget(ratio=0.5, reserve=1.0, cap=10.0)
    assert b.try_spend()
    assert not b.try_spend()  # reserve exhausted
    for _ in range(3):
        b.record_request()  # deposits 0.5 each -> 1.5 tokens
    assert b.try_spend()
    assert b.try_spend() is False  # 0.5 left < 1 retry


def test_deadline_scope_and_check():
    assert overload.ambient_deadline() == 0.0
    with overload.deadline_scope(time.time() + 5.0):
        assert overload.remaining() > 4.0
        overload.check_deadline("fine")
        with overload.deadline_scope(time.time() - 1.0):
            with pytest.raises(DeadlineExceededError):
                overload.check_deadline("expired")
        assert overload.remaining() > 4.0  # restored
    assert overload.ambient_deadline() == 0.0
    assert overload.remaining(42.0) == 42.0  # default when none


def test_chaos_match_scopes_to_context():
    faults.apply_plan([{
        "point": "serve_replica", "mode": "always", "action": "error",
        "match": {"replica": "node1:11"},
    }])
    try:
        # Non-matching context: no fire.
        assert faults.fire("serve_replica", replica="node2:99") == 0.0
        with pytest.raises(faults.InjectedFault):
            faults.fire("serve_replica", replica="node1:11")
    finally:
        faults.clear()


# ------------------------------------------------- cluster-level matrix


@pytest.fixture
def serve_cluster(ray_tpu_start):
    yield ray_tpu_start
    try:
        _arm([])
    except Exception:
        pass
    faults.clear()
    serve.shutdown()


def _nm():
    from ray_tpu.core.runtime_context import current_runtime

    return current_runtime()._nm


def _arm(specs):
    nm = _nm()
    return nm.call_sync(nm._gcs.chaos_arm(specs), timeout=30)


def test_deadline_propagates_to_tasks_and_refuses_expired(serve_cluster):
    """Core plane: a task submitted under an expired ambient budget is
    refused worker-side (never executes); a live budget propagates into
    the executing task (nested calls inherit it)."""
    marker = "/tmp/rtpu_overload_marker_%d" % os.getpid()
    if os.path.exists(marker):
        os.unlink(marker)

    @ray_tpu.remote
    def side_effect():
        with open(marker, "a") as f:
            f.write("ran\n")
        return overload.ambient_deadline()

    with overload.deadline_scope(time.time() - 0.5):
        ref = side_effect.remote()
    with pytest.raises(DeadlineExceededError):
        ray_tpu.get(ref, timeout=30)
    assert not os.path.exists(marker), "expired task must never execute"

    dl = time.time() + 30.0
    with overload.deadline_scope(dl):
        seen = ray_tpu.get(side_effect.remote(), timeout=30)
    assert abs(seen - dl) < 1e-6, "deadline must propagate into the task"
    assert os.path.exists(marker)


def test_deadline_rides_direct_plane_compact_frames(serve_cluster):
    """Templated (compact) direct-plane call frames must carry each
    call's OWN deadline, not the template registrant's."""

    @ray_tpu.remote
    class Probe:
        def deadline(self):
            return overload.ambient_deadline()

    p = Probe.remote()
    dl1 = time.time() + 50.0
    dl2 = time.time() + 99.0
    with overload.deadline_scope(dl1):
        ref1 = p.deadline.remote()  # registers the template
    with overload.deadline_scope(dl2):
        ref2 = p.deadline.remote()  # compact frame
    assert abs(ray_tpu.get(ref1, timeout=30) - dl1) < 1e-6
    assert abs(ray_tpu.get(ref2, timeout=30) - dl2) < 1e-6


def test_expired_serve_request_cancelled_replica_side(serve_cluster):
    """A serve request queued behind a slow one past its budget is
    refused BEFORE user code runs (provably never executes)."""
    marker = "/tmp/rtpu_overload_serve_%d" % os.getpid()
    if os.path.exists(marker):
        os.unlink(marker)

    @serve.deployment(num_replicas=1)
    class Slowish:
        def __call__(self, req):
            with open(marker, "a") as f:
                f.write(f"{req['id']}\n")
                f.flush()
            time.sleep(req.get("sleep", 0))
            return req["id"]

    handle = serve.run(Slowish.bind(), name="slowish")
    # Occupy the single replica...
    f1 = handle.remote({"id": "blocker", "sleep": 1.0})
    time.sleep(0.15)
    # ...then queue a request whose budget dies while it waits.
    with overload.deadline_scope(time.time() + 0.3):
        f2 = handle.remote({"id": "expired", "sleep": 0})
    assert f1.result(timeout=30) == "blocker"
    with pytest.raises(DeadlineExceededError):
        f2.result(timeout=30)
    time.sleep(0.3)
    executed = open(marker).read() if os.path.exists(marker) else ""
    assert "expired" not in executed, \
        "deadline-expired request must never reach user code"


def test_streaming_cancelled_mid_flight_on_deadline(serve_cluster):
    """A streaming response that outlives its budget stops producing at
    an item seam instead of generating to completion."""
    marker = "/tmp/rtpu_overload_stream_%d" % os.getpid()
    if os.path.exists(marker):
        os.unlink(marker)

    @serve.deployment(num_replicas=1)
    class Tokens:
        def gen(self, _):
            for i in range(50):
                with open(marker, "a") as f:
                    f.write(f"{i}\n")
                    f.flush()
                time.sleep(0.1)
                yield i

    handle = serve.run(Tokens.bind(), name="tokens")
    got = []
    with overload.deadline_scope(time.time() + 0.45):
        with pytest.raises(Exception):
            for item in handle.options(method="gen").stream(None):
                got.append(item)
    assert 1 <= len(got) < 50, got
    time.sleep(0.5)  # generator must be dead, not still producing
    n_before = len(open(marker).read().splitlines())
    time.sleep(0.5)
    n_after = len(open(marker).read().splitlines())
    assert n_after == n_before < 50, "generator kept running past cancel"


def test_replica_sheds_past_adaptive_limit(serve_cluster):
    """A replica at its concurrency ceiling refuses with
    OverloadedError (shed, not queue) and the shed counter moves."""

    @serve.deployment(num_replicas=1, max_concurrent_queries=1,
                      ray_actor_options={"max_concurrency": 8})
    class OneAtATime:
        def __call__(self, _):
            time.sleep(0.4)
            return "ok"

    handle = serve.run(OneAtATime.bind(), name="one-at-a-time")
    futs = [handle.remote(None) for _ in range(6)]
    results, errors = [], []
    for f in futs:
        try:
            results.append(f.result(timeout=30))
        except OverloadedError as e:
            errors.append(e)
    assert results, "some requests must be served"
    assert errors, "excess concurrency must shed with OverloadedError"
    assert all(e.retry_after_s > 0 for e in errors)


def test_proxy_sheds_with_503_and_retry_after(serve_cluster):
    """Past the proxy's AIMD limit + bounded queue, HTTP ingress sheds
    with 503 + Retry-After before queueing."""
    from ray_tpu.core.config import get_config

    cfg = get_config()
    old = (cfg.serve_proxy_concurrency, cfg.serve_shed_queue_len)
    cfg.serve_proxy_concurrency, cfg.serve_shed_queue_len = 2, 0
    try:
        from ray_tpu.serve import http_proxy

        http_proxy._gates.clear()  # rebuild gates under the test knobs

        @serve.deployment(num_replicas=1,
                          ray_actor_options={"max_concurrency": 8})
        class Slow:
            def __call__(self, _):
                time.sleep(0.6)
                return "ok"

        handle = serve.run(Slow.bind(), name="slowdep")
        port = handle.http_port

        codes, retry_afters = [], []
        lock = threading.Lock()

        def hit():
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/slowdep",
                data=b"null",
                headers={"Content-Type": "application/json"},
            )
            try:
                with urllib.request.urlopen(req, timeout=30) as resp:
                    with lock:
                        codes.append(resp.status)
            except urllib.error.HTTPError as e:
                with lock:
                    codes.append(e.code)
                    if e.code == 503:
                        retry_afters.append(e.headers.get("Retry-After"))

        threads = [threading.Thread(target=hit) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert codes.count(200) >= 1, codes
        assert codes.count(503) >= 1, codes
        assert retry_afters and all(
            ra is not None and int(ra) >= 1 for ra in retry_afters
        ), retry_afters
    finally:
        cfg.serve_proxy_concurrency, cfg.serve_shed_queue_len = old
        from ray_tpu.serve import http_proxy

        http_proxy._gates.clear()


def test_breaker_opens_shifts_traffic_and_recovers(serve_cluster):
    """THE acceptance scenario: chaos-armed latency on one replica of a
    2-replica deployment under sustained deadlined load -> the sick
    replica's breaker opens, traffic shifts (goodput >= 95%), expired
    requests never execute user code; after heal, half-open probes
    re-admit the replica."""
    marker = "/tmp/rtpu_overload_breaker_%d" % os.getpid()
    if os.path.exists(marker):
        os.unlink(marker)

    @serve.deployment(num_replicas=2, max_concurrent_queries=4,
                      ray_actor_options={"max_concurrency": 4})
    class Echo:
        def __call__(self, req):
            with open(marker, "a") as f:
                f.write(f"{req}\n")
                f.flush()
            return os.getpid()

    handle = serve.run(Echo.bind(), name="breaker-echo")
    state = handle._state

    # Warm both replicas, learn their identities.
    pids = {handle.remote(f"warm-{i}").result(timeout=30)
            for i in range(8)}
    assert len(pids) == 2
    stats = [ray_tpu.get(r.stats.remote(), timeout=30)
             for r in list(state.replicas)]
    sick_id = stats[0]["replica_id"]

    # Inject 0.6s latency into ONE replica only (match-scoped).
    _arm([{"point": "serve_replica", "mode": "always",
           "action": "latency", "delay_s": 0.6,
           "match": {"replica": sick_id}}])

    # Wait until the armed plan has propagated to the workers.
    @ray_tpu.remote
    def current_plan():
        from ray_tpu.util import faults as f

        return f.current_plan()

    deadline = time.time() + 20
    while time.time() < deadline:
        if ray_tpu.get(current_plan.remote(), timeout=30):
            break
        time.sleep(0.1)

    def drive(n, budget_s, tag):
        """n requests under budget_s each; returns (ok, expired)."""
        ok, expired = [], []
        for i in range(n):
            with overload.deadline_scope(time.time() + budget_s):
                fut = handle.remote(f"{tag}-{i}")
            try:
                ok.append(fut.result(timeout=30))
            except (DeadlineExceededError, TimeoutError):
                expired.append(f"{tag}-{i}")
            except OverloadedError:
                expired.append(f"{tag}-{i}")
        return ok, expired

    # Phase 1 (warmup): the sick replica eats its requests' budgets;
    # failures feed its breaker until it opens. Drive until it does
    # (bounded): the warm phase left successes in the rolling window
    # that the failures must outweigh first.
    t0 = time.time()
    while time.time() - t0 < 30.0:
        drive(6, 0.35, "warmup")
        if any(br.state == "open" for br in state.breakers.values()):
            break
    breaker_states = {
        (k.hex() if hasattr(k, "hex") else str(k)): br.state
        for k, br in state.breakers.items()
    }
    assert "open" in breaker_states.values(), breaker_states

    # Phase 2 (steady): breaker open -> traffic on the healthy replica;
    # goodput of in-deadline requests >= 95% (the occasional half-open
    # probe may still burn one request on the sick replica — that's the
    # probe doing its job, and it's why the phase is 60 requests wide).
    ok, expired = drive(60, 0.35, "steady")
    goodput = len(ok) / 60.0
    assert goodput >= 0.95, (goodput, expired)
    assert len(set(ok)) == 1, "traffic must have shifted off the sick one"

    # Expired requests provably never executed user code.
    executed = open(marker).read()
    for rid in expired:
        assert rid not in executed, f"expired request {rid} executed"

    # Phase 3 (heal): disarm, wait out the open window, drive probes —
    # the breaker closes and BOTH replicas serve again.
    _arm([])
    deadline = time.time() + 30
    healed = False
    while time.time() < deadline:
        ok, _ = drive(6, 2.0, "heal")
        if len({pid for pid in ok}) == 2:
            healed = True
            break
        time.sleep(0.5)
    assert healed, "half-open probes must re-admit the healed replica"
    assert all(br.state == "closed" for br in state.breakers.values())


def test_controller_ejects_persistently_open_replica(serve_cluster):
    """Replicas whose breakers stay open are ejected through the drain
    machinery (surge-replace): the controller swaps in a fresh replica
    and retires the sick one."""
    import ray_tpu as rt
    from ray_tpu.serve.controller import CONTROLLER_NAME

    @serve.deployment(num_replicas=2)
    def echo(x):
        return x

    handle = serve.run(echo.bind(), name="ejectable")
    controller = rt.get_actor(CONTROLLER_NAME)
    routing = rt.get(controller.get_routing.remote("ejectable"),
                     timeout=30)
    victim_hex = routing["replicas"][0]._actor_id.hex()

    # Shrink the ejection threshold inside the controller process.
    rt.get(controller.set_breaker_eject_s.remote(0.5), timeout=30)
    # Report the victim's breaker OPEN continuously (fresh reports with
    # an old first-seen), like a handle's refresh loop would.
    for _ in range(6):
        rt.get(controller.report_breakers.remote(
            "ejectable", "test-handle", {victim_hex: "open"}
        ), timeout=30)
        time.sleep(0.3)

    deadline = time.time() + 30
    while time.time() < deadline:
        routing = rt.get(controller.get_routing.remote("ejectable"),
                         timeout=30)
        hexes = {r._actor_id.hex() for r in routing["replicas"]}
        if victim_hex not in hexes and len(hexes) == 2:
            break
        time.sleep(0.3)
    else:
        raise AssertionError("sick replica was never ejected/replaced")
    # Deployment still answers.
    assert handle.remote("alive").result(timeout=30) == "alive"
