"""util extras: ActorPool, Queue, metrics, runtime_env (ref analogue:
python/ray/tests/test_actor_pool.py, test_queue.py, test_metrics_agent.py,
test_runtime_env_working_dir.py)."""

import sys
import time

import pytest

import ray_tpu
from ray_tpu.util import ActorPool
from ray_tpu.util.queue import Empty, Full, Queue


def test_actor_pool_ordered_and_unordered(ray_tpu_start):
    @ray_tpu.remote
    class Doubler:
        def double(self, x):
            return x * 2

    pool = ActorPool([Doubler.remote() for _ in range(3)])
    assert list(pool.map(lambda a, v: a.double.remote(v), range(8))) == \
        [0, 2, 4, 6, 8, 10, 12, 14]
    out = sorted(pool.map_unordered(
        lambda a, v: a.double.remote(v), range(8)
    ))
    assert out == [0, 2, 4, 6, 8, 10, 12, 14]


def test_actor_pool_submit_get_next(ray_tpu_start):
    @ray_tpu.remote
    class Echo:
        def echo(self, x):
            return x

    pool = ActorPool([Echo.remote() for _ in range(2)])
    pool.submit(lambda a, v: a.echo.remote(v), "a")
    pool.submit(lambda a, v: a.echo.remote(v), "b")
    assert pool.get_next() == "a"
    assert pool.get_next() == "b"
    assert not pool.has_next()


def test_queue_fifo_and_limits(ray_tpu_start):
    q = Queue(maxsize=2)
    q.put(1)
    q.put(2)
    with pytest.raises(Full):
        q.put(3, block=False)
    assert q.get() == 1
    assert q.get() == 2
    with pytest.raises(Empty):
        q.get(block=False)
    with pytest.raises(Empty):
        q.get(timeout=0.2)


def test_queue_cross_actor(ray_tpu_start):
    q = Queue()

    @ray_tpu.remote
    def producer(q, n):
        for i in range(n):
            q.put(i)
        return "done"

    ref = producer.remote(q, 5)
    got = [q.get(timeout=30) for _ in range(5)]
    assert got == [0, 1, 2, 3, 4]
    assert ray_tpu.get(ref) == "done"


def test_metrics_counter_gauge_histogram(ray_tpu_start):
    from ray_tpu.util import metrics

    c = metrics.Counter("requests_total", tag_keys=("route",))
    c.inc(tags={"route": "/a"})
    c.inc(2.0, tags={"route": "/a"})
    g = metrics.Gauge("replicas")
    g.set(3.0)
    h = metrics.Histogram("latency_s", boundaries=[0.1, 1.0])
    h.observe(0.05)
    h.observe(5.0)

    # Metrics recorded inside workers aggregate with the driver's.
    @ray_tpu.remote
    def work():
        from ray_tpu.util import metrics as m

        m.Counter("requests_total", tag_keys=("route",)).inc(
            5.0, tags={"route": "/a"}
        )
        m._registry.flush()
        return 1

    ray_tpu.get(work.remote())
    report = metrics.get_metrics_report()
    series = report["requests_total"]["series"]
    assert series[(("route", "/a"),)] == 8.0
    assert report["replicas"]["series"][()] == 3.0
    hist = report["latency_s"]["series"][()]
    assert hist["count"] == 2 and hist["buckets"][0] == 1 \
        and hist["buckets"][-1] == 1


def test_runtime_env_working_dir_and_env_vars(tmp_path):
    """Workers import modules from the shipped working_dir and see the
    injected env vars (ref: runtime_env working_dir packaging)."""
    pkg = tmp_path / "proj"
    pkg.mkdir()
    (pkg / "mylib.py").write_text(
        "MAGIC = 'runtime-env-works'\n"
        "def compute():\n"
        "    return MAGIC\n"
    )
    ray_tpu.init(
        num_cpus=2,
        runtime_env={
            "working_dir": str(pkg),
            "env_vars": {"MY_RUNTIME_FLAG": "on"},
        },
    )
    try:
        @ray_tpu.remote
        def use_lib():
            import os

            import mylib  # resolvable only via the shipped working_dir

            return mylib.compute(), os.environ.get("MY_RUNTIME_FLAG")

        value, flag = ray_tpu.get(use_lib.remote())
        assert value == "runtime-env-works"
        assert flag == "on"
    finally:
        ray_tpu.shutdown()


def test_runtime_env_py_modules(tmp_path):
    """py_modules ship as importable packages (import <name> works in
    workers)."""
    pkg = tmp_path / "shippedpkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("from .core import VALUE\n")
    (pkg / "core.py").write_text("VALUE = 'py-modules-ok'\n")
    ray_tpu.init(num_cpus=2,
                 runtime_env={"py_modules": [str(pkg)]})
    try:
        @ray_tpu.remote
        def use_pkg():
            import shippedpkg

            return shippedpkg.VALUE

        assert ray_tpu.get(use_pkg.remote()) == "py-modules-ok"
    finally:
        ray_tpu.shutdown()


def test_runtime_env_reaches_prestarted_workers(tmp_path):
    """Workers prestarted before the driver published the env still apply
    it at first task execution."""
    pkg = tmp_path / "lateenv"
    pkg.mkdir()
    (pkg / "latelib.py").write_text("OK = 'late-apply'\n")
    ray_tpu.init(
        num_cpus=2,
        system_config={"num_prestart_workers": 2},
        runtime_env={"working_dir": str(pkg)},
    )
    try:
        import time

        time.sleep(1.0)  # let prestarted workers boot

        @ray_tpu.remote
        def use_late():
            import latelib

            return latelib.OK

        assert ray_tpu.get(use_late.remote()) == "late-apply"
    finally:
        ray_tpu.shutdown()


def _make_wheel(tmp_path, name="rtpu_demo_pkg", version="0.1"):
    """Handcraft a minimal pure-python wheel (no network, no build
    tooling): a zip of <pkg>/__init__.py + .dist-info metadata."""
    import base64
    import hashlib
    import zipfile

    whl = tmp_path / f"{name}-{version}-py3-none-any.whl"
    dist = f"{name}-{version}.dist-info"
    files = {
        f"{name}/__init__.py": b"MAGIC = 'installed-via-pip-runtime-env'\n",
        f"{dist}/METADATA": (
            f"Metadata-Version: 2.1\nName: {name}\nVersion: {version}\n"
        ).encode(),
        f"{dist}/WHEEL": (
            b"Wheel-Version: 1.0\nGenerator: test\nRoot-Is-Purelib: true\n"
            b"Tag: py3-none-any\n"
        ),
    }
    record_lines = []
    with zipfile.ZipFile(whl, "w") as zf:
        for arc, data in files.items():
            zf.writestr(arc, data)
            digest = base64.urlsafe_b64encode(
                hashlib.sha256(data).digest()).rstrip(b"=").decode()
            record_lines.append(f"{arc},sha256={digest},{len(data)}")
        record_lines.append(f"{dist}/RECORD,,")
        zf.writestr(f"{dist}/RECORD", "\n".join(record_lines) + "\n")
    return str(whl)


@pytest.mark.slow
def test_runtime_env_pip_local_wheel(tmp_path):
    """A job's pip runtime env installs a package absent from the base
    env into a per-node hash-keyed venv; workers import it (VERDICT r3
    ask #5; ref: _private/runtime_env/pip.py). Local wheel keeps the
    sandbox offline."""
    wheel = _make_wheel(tmp_path)
    ray_tpu.init(
        num_cpus=2,
        runtime_env={"pip": [wheel]},
        system_config={"log_to_driver": False},
    )
    try:
        @ray_tpu.remote
        def probe():
            import rtpu_demo_pkg

            return rtpu_demo_pkg.MAGIC

        assert ray_tpu.get(probe.remote(), timeout=300) == \
            "installed-via-pip-runtime-env"

        # Second task on the same node reuses the cached venv (fast).
        import time as _t

        t0 = _t.time()
        assert ray_tpu.get(probe.remote(), timeout=60)
        assert _t.time() - t0 < 30
    finally:
        ray_tpu.shutdown()
