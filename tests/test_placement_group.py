"""Placement groups: reservation, strategies, bundle-scoped scheduling
(ref analogue: python/ray/tests/test_placement_group*.py over the
single-machine multi-node Cluster fixture)."""

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.core.resources import ResourceSet
from ray_tpu.core.scheduling_policy import place_bundles
from ray_tpu.util import (
    PlacementGroupSchedulingStrategy,
    placement_group,
    placement_group_table,
    remove_placement_group,
)


@pytest.fixture
def cluster():
    c = Cluster(
        head_resources={"CPU": 2},
        system_config={"num_prestart_workers": 1, "default_max_retries": 0},
    )
    yield c
    c.shutdown()


def test_place_bundles_policies_pure():
    nodes = [
        {"node_id": "aa", "state": "alive",
         "resources_available": {"CPU": 4}, "resources_total": {"CPU": 4}},
        {"node_id": "bb", "state": "alive",
         "resources_available": {"CPU": 4}, "resources_total": {"CPU": 4}},
    ]
    two = [ResourceSet({"CPU": 2}), ResourceSet({"CPU": 2})]
    assert place_bundles(two, "STRICT_PACK", nodes) == ["aa", "aa"]
    assert place_bundles(two, "STRICT_SPREAD", nodes) == ["aa", "bb"]
    spread = place_bundles(two, "SPREAD", nodes)
    assert sorted(set(spread)) == ["aa", "bb"]
    # STRICT_PACK impossible when one node can't hold all bundles.
    three = [ResourceSet({"CPU": 3}), ResourceSet({"CPU": 3})]
    assert place_bundles(three, "STRICT_PACK", nodes) is None
    # STRICT_SPREAD impossible with more bundles than nodes.
    four = [ResourceSet({"CPU": 1})] * 3
    assert place_bundles(four, "STRICT_SPREAD", nodes) is None


def test_pg_single_node_reserve_and_run(cluster):
    pg = placement_group([{"CPU": 1}], strategy="PACK")
    assert pg.wait(30)

    @ray_tpu.remote(
        num_cpus=1,
        scheduling_strategy=PlacementGroupSchedulingStrategy(pg, 0),
    )
    def inside():
        return "ran"

    assert ray_tpu.get(inside.remote(), timeout=60) == "ran"
    table = placement_group_table()
    assert table[pg.id]["state"] == "created"
    remove_placement_group(pg)


def test_pg_ready_probe(cluster):
    pg = placement_group([{"CPU": 1}])
    assert ray_tpu.get(pg.ready(), timeout=60) == pg.id


def test_pg_strict_spread_lands_on_distinct_nodes(cluster):
    cluster.add_node(num_cpus=2)
    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="STRICT_SPREAD")
    assert pg.wait(30)

    @ray_tpu.remote(num_cpus=1)
    def where():
        import ray_tpu as rt

        return rt.get_runtime_context().get_node_id()

    a = where.options(
        scheduling_strategy=PlacementGroupSchedulingStrategy(pg, 0)
    ).remote()
    b = where.options(
        scheduling_strategy=PlacementGroupSchedulingStrategy(pg, 1)
    ).remote()
    na, nb = ray_tpu.get([a, b], timeout=90)
    assert na != nb


def test_pg_actor_in_bundle(cluster):
    cluster.add_node(num_cpus=2, resources={"gadget": 1})
    pg = placement_group([{"gadget": 1}], strategy="PACK")
    assert pg.wait(30)

    @ray_tpu.remote(
        resources={"gadget": 1},
        scheduling_strategy=PlacementGroupSchedulingStrategy(pg, 0),
    )
    class Pinned:
        def where(self):
            import ray_tpu as rt

            return rt.get_runtime_context().get_node_id()

    p = Pinned.remote()
    assert ray_tpu.get(p.where.remote(), timeout=90) != cluster.head_node_id


def test_pg_pending_until_capacity(cluster):
    pg = placement_group([{"CPU": 1}, {"CPU": 1}, {"CPU": 4}], "PACK")
    assert not pg.wait(1.0)  # head alone (2 CPU) can't host the 4-CPU bundle
    cluster.add_node(num_cpus=6)
    assert pg.wait(30)


def test_pg_removal_frees_resources(cluster):
    pg = placement_group([{"CPU": 2}])
    assert pg.wait(30)
    remove_placement_group(pg)

    # All head CPUs are usable again by plain tasks.
    @ray_tpu.remote(num_cpus=2)
    def f():
        return 7

    assert ray_tpu.get(f.remote(), timeout=60) == 7


def test_pg_worker_can_create_and_use(cluster):
    @ray_tpu.remote
    def driver_like():
        from ray_tpu.util import (
            PlacementGroupSchedulingStrategy as S,
            placement_group as make_pg,
        )
        import ray_tpu as rt

        pg = make_pg([{"CPU": 1}])
        assert pg.wait(30)

        @rt.remote(num_cpus=1, scheduling_strategy=S(pg, 0))
        def inner():
            return 11

        return rt.get(inner.remote(), timeout=60)

    assert ray_tpu.get(driver_like.remote(), timeout=90) == 11


def test_pg_replaced_after_node_death(cluster):
    """A PG whose bundle node dies goes back to pending and is re-placed on
    replacement capacity; tasks targeting it run instead of spinning
    forward/requeue forever (advisor r1 high finding)."""
    import time

    handle = cluster.add_node(num_cpus=1, resources={"gadget": 1})
    pg = placement_group([{"gadget": 1, "CPU": 1}], strategy="PACK")
    assert pg.wait(30)

    @ray_tpu.remote(
        resources={"gadget": 1},
        scheduling_strategy=PlacementGroupSchedulingStrategy(pg, 0),
    )
    def where():
        import ray_tpu as rt

        return rt.get_runtime_context().get_node_id()

    first = ray_tpu.get(where.remote(), timeout=60)
    cluster.remove_node(handle)
    time.sleep(0.5)
    h2 = cluster.add_node(num_cpus=1, resources={"gadget": 1})
    assert pg.wait(60)
    second = ray_tpu.get(where.remote(), timeout=90)
    assert second != first
    assert second != cluster.head_node_id


def test_pg_task_stays_queued_until_placed():
    """Tasks into a not-yet-placeable PG stay queued — they are not failed
    after a timeout — and run once capacity arrives (advisor r1)."""
    import time

    c = Cluster(
        head_resources={"CPU": 2},
        system_config={
            "num_prestart_workers": 1,
            "default_max_retries": 0,
            "object_locate_timeout_s": 1.0,
        },
    )
    try:
        pg = placement_group([{"CPU": 4}], strategy="PACK")

        @ray_tpu.remote(
            num_cpus=1,
            scheduling_strategy=PlacementGroupSchedulingStrategy(pg, 0),
        )
        def inside():
            return "ran"

        ref = inside.remote()
        # Several multiples of the resolve timeout: the old behavior would
        # have failed the task by now.
        time.sleep(3.0)
        c.add_node(num_cpus=6)
        assert ray_tpu.get(ref, timeout=60) == "ran"
    finally:
        c.shutdown()
