"""Multi-node cluster semantics, exercised the way the reference tests them:
extra node-manager processes on one machine via the Cluster fixture
(ref analogue: python/ray/tests/ using conftest ray_start_cluster over
cluster_utils.Cluster.add_node)."""

import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.core.scheduling_policy import pick_node
from ray_tpu.core.resources import ResourceSet


@pytest.fixture
def cluster():
    c = Cluster(
        head_resources={"CPU": 2},
        system_config={
            "num_prestart_workers": 1,
            "gc_grace_period_s": 60.0,
            "default_max_retries": 0,
        },
    )
    yield c
    c.shutdown()


def test_nodes_register_and_report(cluster):
    cluster.add_node(num_cpus=3, resources={"gadget": 2})
    views = ray_tpu.nodes()
    assert len(views) == 2
    total = ray_tpu.cluster_resources()
    assert total["CPU"] == 5
    assert total["gadget"] == 2


def test_task_spills_to_remote_node_and_result_returns(cluster):
    cluster.add_node(num_cpus=1, resources={"gadget": 1})

    @ray_tpu.remote(resources={"gadget": 1})
    def where():
        import ray_tpu as rt

        return rt.get_runtime_context().get_node_id()

    node_hex = ray_tpu.get(where.remote(), timeout=60)
    assert node_hex != cluster.head_node_id


def test_large_result_pulled_across_nodes(cluster):
    cluster.add_node(num_cpus=1, resources={"gadget": 1})

    @ray_tpu.remote(resources={"gadget": 1})
    def make_array():
        import numpy as np

        return np.arange(300_000, dtype="int64")

    arr = ray_tpu.get(make_array.remote(), timeout=60)
    assert arr.shape == (300_000,)
    assert int(arr[12345]) == 12345


def test_cross_node_dependency(cluster):
    cluster.add_node(num_cpus=1, resources={"gadget": 1})

    @ray_tpu.remote(resources={"gadget": 1})
    def produce():
        import numpy as np

        return np.ones(200_000, dtype="float32")

    @ray_tpu.remote  # runs on the head
    def consume(x):
        return float(x.sum())

    ref = produce.remote()
    assert ray_tpu.get(consume.remote(ref), timeout=60) == 200_000.0


def test_spread_strategy_uses_both_nodes(cluster):
    cluster.add_node(num_cpus=2)

    @ray_tpu.remote(scheduling_strategy="SPREAD")
    def where():
        import ray_tpu as rt
        import time as _t

        _t.sleep(0.2)
        return rt.get_runtime_context().get_node_id()

    refs = [where.remote() for _ in range(8)]
    seen = set(ray_tpu.get(refs, timeout=120))
    assert len(seen) == 2


def test_node_affinity_strategy(cluster):
    handle = cluster.add_node(num_cpus=1)
    target = handle.node_id_hex
    assert target is not None

    @ray_tpu.remote(
        scheduling_strategy=ray_tpu.NodeAffinitySchedulingStrategy(target)
    )
    def where():
        import ray_tpu as rt

        return rt.get_runtime_context().get_node_id()

    assert ray_tpu.get(where.remote(), timeout=60) == target


def test_actor_on_remote_node(cluster):
    cluster.add_node(num_cpus=1, resources={"gadget": 1})

    @ray_tpu.remote(resources={"gadget": 0.5})
    class Counter:
        def __init__(self):
            self.n = 0

        def incr(self, k=1):
            self.n += k
            return self.n

        def node(self):
            import ray_tpu as rt

            return rt.get_runtime_context().get_node_id()

    c = Counter.remote()
    assert ray_tpu.get(c.node.remote(), timeout=60) != cluster.head_node_id
    assert ray_tpu.get(c.incr.remote(), timeout=60) == 1
    assert ray_tpu.get(c.incr.remote(5), timeout=60) == 6


def test_named_actor_visible_across_nodes(cluster):
    cluster.add_node(num_cpus=1, resources={"gadget": 1})

    @ray_tpu.remote(resources={"gadget": 0.5}, name="reg")
    class Registry:
        def ping(self):
            return "pong"

    _ = Registry.remote()
    # Lookup from the driver resolves through the GCS name table.
    time.sleep(0.2)
    h = ray_tpu.get_actor("reg")
    assert ray_tpu.get(h.ping.remote(), timeout=60) == "pong"


def test_infeasible_in_cluster_fails_loudly(cluster):
    cluster.add_node(num_cpus=1)

    @ray_tpu.remote(resources={"no_such_thing": 1})
    def f():
        return 1

    with pytest.raises(ray_tpu.TaskError):
        ray_tpu.get(f.remote(), timeout=60)


def test_node_death_fails_forwarded_task(cluster):
    handle = cluster.add_node(num_cpus=1, resources={"gadget": 1})

    @ray_tpu.remote(resources={"gadget": 1}, max_retries=0)
    def slow():
        import time as _t

        _t.sleep(30)
        return "done"

    ref = slow.remote()
    time.sleep(1.0)  # let it get forwarded and start
    cluster.remove_node(handle)
    with pytest.raises((ray_tpu.WorkerCrashedError, ray_tpu.TaskError)):
        ray_tpu.get(ref, timeout=60)


def test_node_death_retries_on_surviving_node(cluster):
    handle = cluster.add_node(num_cpus=1, resources={"gadget": 1})

    @ray_tpu.remote(resources={"gadget": 0.5}, max_retries=2)
    def work():
        import time as _t

        _t.sleep(3)
        return "ok"

    ref = work.remote()
    time.sleep(1.0)
    # Second node with the same custom resource lets the retry land there.
    cluster.add_node(num_cpus=1, resources={"gadget": 1})
    cluster.remove_node(handle)
    assert ray_tpu.get(ref, timeout=120) == "ok"


def test_pick_node_policies_pure():
    nodes = [
        {
            "node_id": "aa", "state": "alive", "pending_tasks": 0,
            "resources_total": {"CPU": 4}, "resources_available": {"CPU": 0},
            "labels": {},
        },
        {
            "node_id": "bb", "state": "alive", "pending_tasks": 0,
            "resources_total": {"CPU": 4}, "resources_available": {"CPU": 4},
            "labels": {"zone": "z2"},
        },
    ]
    req = ResourceSet({"CPU": 1})
    # Hybrid: local full -> least-utilized remote.
    assert pick_node(req, "DEFAULT", "aa", nodes) == "bb"
    # Affinity hard: dead/absent target -> None.
    from ray_tpu.core.scheduling_strategies import (
        NodeAffinitySchedulingStrategy,
        NodeLabelSchedulingStrategy,
    )

    assert pick_node(req, NodeAffinitySchedulingStrategy("cc"), "aa", nodes) is None
    assert pick_node(req, NodeAffinitySchedulingStrategy("bb"), "aa", nodes) == "bb"
    assert (
        pick_node(req, NodeLabelSchedulingStrategy({"zone": "z2"}), "aa", nodes)
        == "bb"
    )
    # Infeasible everywhere.
    big = ResourceSet({"CPU": 64})
    assert pick_node(big, "DEFAULT", "aa", nodes) is None


def test_lineage_reconstruction_after_node_death(cluster):
    """A lost task-return object is rebuilt by re-executing its creating
    task (ref analogue: core_worker/object_recovery_manager.h +
    lineage pinning in reference_count.h:61)."""
    handle = cluster.add_node(num_cpus=1, resources={"gadget": 1})

    @ray_tpu.remote(resources={"gadget": 1}, max_retries=0)
    def produce():
        import numpy as np

        return np.arange(200_000, dtype="int64")

    ref = produce.remote()
    ready, _ = ray_tpu.wait([ref], num_returns=1, timeout=60)
    assert ready
    cluster.remove_node(handle)
    cluster.add_node(num_cpus=1, resources={"gadget": 1})
    time.sleep(0.5)
    out = ray_tpu.get(ref, timeout=120)
    assert out.shape == (200_000,)
    assert int(out[-1]) == 199_999


def test_lineage_chain_reconstruction(cluster):
    """Recovery recurses through dependencies: a lost object whose lost
    argument must also be re-executed."""
    handle = cluster.add_node(num_cpus=1, resources={"gadget": 1})

    @ray_tpu.remote(resources={"gadget": 1}, max_retries=0)
    def base():
        import numpy as np

        return np.ones(150_000, dtype="int64")

    @ray_tpu.remote(resources={"gadget": 1}, max_retries=0)
    def double(x):
        return x * 2

    a = base.remote()
    b = double.remote(a)
    ready, _ = ray_tpu.wait([b], num_returns=1, timeout=60)
    assert ready
    cluster.remove_node(handle)
    cluster.add_node(num_cpus=1, resources={"gadget": 1})
    time.sleep(0.5)
    out = ray_tpu.get(b, timeout=120)
    assert int(out.sum()) == 300_000


def test_session_token_gates_gcs_connections():
    """With session_token set, hello frames lacking the token are
    rejected before any pickle payload is processed (advisor r1: the
    framed-pickle plane must not accept anonymous connections)."""
    import socket as socklib
    import struct

    import cloudpickle

    import ray_tpu
    from ray_tpu.core.runtime_context import current_runtime

    ray_tpu.init(num_cpus=1, system_config={"session_token": "s3cret"})
    try:
        host, port = current_runtime()._nm.gcs_service.address

        def hello(token):
            payload = cloudpickle.dumps(
                {"type": "gcs_hello", "node_id": "ab" * 16,
                 **({"token": token} if token else {})},
                protocol=5,
            )
            s = socklib.create_connection((host, port), timeout=5)
            s.sendall(struct.pack("<I", len(payload)) + payload)
            s.settimeout(5)
            try:
                data = s.recv(4096)
            finally:
                s.close()
            return data

        # Wrong/absent token: an explicit rejection frame, then close.
        assert b"session token" in hello(None)
        assert b"session token" in hello("wrong")
        # Correct token: welcomed.
        assert b"gcs_welcome" in hello("s3cret")
    finally:
        ray_tpu.shutdown()
