"""RLlib family tests, batch 4: Ape-X DDPG, DD-PPO, SlateQ."""

import sys as _sys

import cloudpickle as _cloudpickle
import numpy as np
import pytest

_cloudpickle.register_pickle_by_value(_sys.modules[__name__])


def _go_to_zero_env():
    """1-D continuous toy: reward -|x + a|; optimum a = -x."""
    import numpy as _np

    class _Box:
        def __init__(self, low, high, shape):
            self.low = _np.full(shape, low, dtype=_np.float32)
            self.high = _np.full(shape, high, dtype=_np.float32)
            self.shape = shape

    class GoToZero:
        def __init__(self):
            self.observation_space = _Box(-1.0, 1.0, (1,))
            self.action_space = _Box(-1.0, 1.0, (1,))
            self._rng = _np.random.RandomState(0)
            self._t = 0

        def reset(self, seed=None):
            if seed is not None:
                self._rng = _np.random.RandomState(seed)
            self._t = 0
            self._x = self._rng.uniform(-1, 1, (1,)).astype("float32")
            return self._x, {}

        def step(self, action):
            r = -float(abs(self._x[0] + float(action[0])))
            self._t += 1
            self._x = self._rng.uniform(-1, 1, (1,)).astype("float32")
            return self._x, r, False, self._t >= 50, {}

    return GoToZero()


def _sign_env():
    """Discrete toy: action must match the sign of obs; 30-step
    episodes."""
    import numpy as _np

    class _Box:
        def __init__(self, shape):
            self.shape = shape

    class _Disc:
        n = 2
        shape = ()

    class Sign:
        def __init__(self):
            self.observation_space = _Box((1,))
            self.action_space = _Disc()
            self._rng = _np.random.RandomState(0)
            self._t = 0

        def _obs(self):
            self._sig = float(self._rng.choice([-1.0, 1.0]))
            return _np.asarray([self._sig], "float32")

        def reset(self, seed=None):
            if seed is not None:
                self._rng = _np.random.RandomState(seed)
            self._t = 0
            return self._obs(), {}

        def step(self, action):
            want = 1 if self._sig > 0 else 0
            r = 1.0 if int(action) == want else -1.0
            self._t += 1
            return self._obs(), r, False, self._t >= 30, {}

    return Sign()


@pytest.mark.slow
def test_apex_ddpg_learns(ray_tpu_start):
    """Ape-X DDPG: replay actor + noise ladder + async rollouts on
    continuous control (ref: rllib/algorithms/apex_ddpg)."""
    from ray_tpu.rllib import ApexDDPGConfig

    config = (
        ApexDDPGConfig()
        .environment(_go_to_zero_env)
        .env_runners(num_env_runners=3, rollout_fragment_length=80)
        .training(lr=3e-3, minibatch_size=128,
                  num_updates_per_iteration=48,
                  num_steps_sampled_before_learning_starts=200)
    )
    algo = config.build()
    try:
        # Ladder: first runner noisiest.
        assert algo._ladder[0] > algo._ladder[-1]
        first = algo.train()
        last = {}
        for _ in range(15):
            last = algo.train()
        assert last["num_learner_updates"] > 0
        assert last["episode_reward_mean"] > \
            first["episode_reward_mean"] + 3, (first, last)
        assert last["episode_reward_mean"] > -15, last
    finally:
        algo.stop()


@pytest.mark.slow
def test_ddppo_learns_sign_task(ray_tpu_start):
    """DD-PPO: per-worker learners with averaged gradients stay in
    lockstep and learn (ref: rllib/algorithms/ddppo)."""
    from ray_tpu.rllib import DDPPOConfig

    config = (
        DDPPOConfig()
        .environment(_sign_env)
        .env_runners(num_env_runners=2, rollout_fragment_length=120)
        .training(lr=5e-3)
        .debugging(seed=0)
    )
    config.sgd_rounds_per_iteration = 4
    algo = config.build()
    try:
        best = -31.0
        for _ in range(15):
            result = algo.train()
            if result["episodes_total"] > 0:
                best = max(best, result["episode_reward_mean"])
            if best > 24:
                break
        assert best > 24, best

        # Lockstep invariant: every worker holds identical params.
        import ray_tpu

        w = ray_tpu.get(
            [wk.get_weights.remote() for wk in algo.workers]
        )
        (W0, _), = w[0]["pi"]
        (W1, _), = w[1]["pi"]
        np.testing.assert_allclose(W0, W1, atol=1e-6)
    finally:
        algo.stop()


def _recsys_env():
    """Toy recsys: user prefers items aligned with a hidden taste
    vector; clicks follow a logit over slate scores; reward = clicked
    item's alignment. SlateQ must learn to put aligned items in the
    slate."""
    import numpy as _np

    class RecSys:
        num_items = 12
        slate_size = 3

        def __init__(self):
            rng = _np.random.RandomState(7)
            self.item_features = rng.randn(
                self.num_items, 4
            ).astype("float32")
            self._rng = _np.random.RandomState(0)
            self._t = 0

        def _user(self):
            taste = self._rng.randn(4)
            self._taste = (taste / _np.linalg.norm(taste)).astype(
                "float32"
            )
            return self._taste

        def reset(self, seed=None):
            if seed is not None:
                self._rng = _np.random.RandomState(seed)
            self._t = 0
            return self._user(), {}

        def step(self, slate):
            aligns = _np.asarray([
                float(self.item_features[i] @ self._taste)
                for i in slate
            ])
            # Conditional logit incl. a no-click option (score 0).
            ex = _np.exp(aligns - aligns.max())
            probs = ex / (ex.sum() + _np.exp(-aligns.max()))
            u = self._rng.rand()
            acc = 0.0
            clicked, reward = -1, 0.0
            for j, p in enumerate(probs):
                acc += p
                if u < acc:
                    clicked = int(slate[j])
                    reward = float(aligns[j])
                    break
            self._t += 1
            done = self._t >= 20
            return self._user(), reward, False, done, \
                {"clicked": clicked}

    return RecSys()


@pytest.mark.slow
def test_slateq_learns_recommendation(ray_tpu_start):
    """SlateQ's decomposition learns to fill slates with high-value
    items (ref: rllib/algorithms/slateq)."""
    from ray_tpu.rllib import SlateQConfig

    config = (
        SlateQConfig()
        .environment(_recsys_env)
        .env_runners(num_env_runners=2, rollout_fragment_length=100)
        .training(lr=3e-3, minibatch_size=128,
                  num_updates_per_iteration=32,
                  num_steps_sampled_before_learning_starts=300,
                  epsilon_timesteps=2000)
        .debugging(seed=0)
    )
    algo = config.build()
    try:
        best = -99.0
        for _ in range(40):
            result = algo.train()
            if result["episodes_total"] > 0:
                best = max(best, result["episode_reward_mean"])
            if best > 18:
                break
        # Random slates: clicks on random items, mean alignment ~0 →
        # episode reward ~0-8. Greedy aligned slates: ~1.2/step * 20.
        assert best > 18, best
        assert np.isfinite(result["loss"])
    finally:
        algo.stop()


def test_pg_learns_sign_task(ray_tpu_start):
    """Vanilla REINFORCE solves sign matching (ref:
    rllib/algorithms/pg)."""
    from ray_tpu.rllib import PGConfig

    config = (
        PGConfig()
        .environment(_sign_env)
        .env_runners(num_env_runners=2, rollout_fragment_length=120)
        .training(lr=5e-3, train_batch_size=240, minibatch_size=240)
        .debugging(seed=0)
    )
    algo = config.build()
    try:
        best = -31.0
        for _ in range(25):
            result = algo.train()
            if result["episodes_total"] > 0:
                best = max(best, result["episode_reward_mean"])
            if best > 24:
                break
        assert best > 24, best
    finally:
        algo.stop()


@pytest.mark.slow
def test_a3c_learns_sign_task(ray_tpu_start):
    """A3C: per-worker gradients applied asynchronously as they land
    (ref: rllib/algorithms/a3c)."""
    from ray_tpu.rllib import A3CConfig

    config = (
        A3CConfig()
        .environment(_sign_env)
        .env_runners(num_env_runners=2, rollout_fragment_length=120)
        .training(lr=5e-3)
        .debugging(seed=0)
    )
    config.grads_per_iteration = 6
    algo = config.build()
    try:
        best = -31.0
        for _ in range(25):
            result = algo.train()
            if result["episodes_total"] > 0:
                best = max(best, result["episode_reward_mean"])
            if best > 24:
                break
        assert best > 24, best
        assert result["num_grads_applied"] > 0
    finally:
        algo.stop()


def _memory_env():
    """POMDP: cue visible only at t=0; every later step rewards the
    action matching the remembered cue."""
    import numpy as _np

    class _Box:
        def __init__(self, shape):
            self.shape = shape

    class _Disc:
        n = 2
        shape = ()

    class Memory:
        def __init__(self):
            self.observation_space = _Box((1,))
            self.action_space = _Disc()
            self._rng = _np.random.RandomState(0)
            self._t = 0

        def reset(self, seed=None):
            if seed is not None:
                self._rng = _np.random.RandomState(seed)
            self._t = 0
            self._cue = float(self._rng.choice([-1.0, 1.0]))
            return _np.asarray([self._cue], "float32"), {}

        def step(self, action):
            want = 1 if self._cue > 0 else 0
            r = 1.0 if int(action) == want else -1.0
            self._t += 1
            done = self._t >= 8
            return _np.asarray([0.0], "float32"), r, False, done, {}

    return Memory()


@pytest.mark.slow
def test_recurrent_ppo_learns_memory_task(ray_tpu_start):
    """PPO with an LSTM policy (the reference's use_lstm option)
    solves a memory task feedforward PPO cannot."""
    from ray_tpu.rllib import RecurrentPPOConfig

    config = (
        RecurrentPPOConfig()
        .environment(_memory_env)
        .env_runners(num_env_runners=2, rollout_fragment_length=128)
        .training(lr=3e-3, minibatch_size=256, num_epochs=4,
                  seq_len=8)
        .debugging(seed=0)
    )
    algo = config.build()
    try:
        best = -9.0
        for _ in range(40):
            result = algo.train()
            if result["episodes_total"] > 0:
                best = max(best, result["episode_reward_mean"])
        # Sampling keeps entropy, so judge the learned capability
        # GREEDILY: at the final step (pure memory, obs is 0) the
        # argmax action must match the step-0 cue.
        assert best > 2.5, best  # memoryless caps near ~1
        from ray_tpu.rllib.r2d2 import _lstm_step_np

        w = algo.learner.get_weights()
        (Wp, bp), = w["pi"]
        env = _memory_env()
        last_correct = 0
        trials = 60
        for ep in range(trials):
            obs, _ = env.reset(seed=2000 + ep)
            want = 1 if float(obs[0]) > 0 else 0
            h = np.zeros(len(w["wh"]), np.float32)
            c = np.zeros(len(w["wh"]), np.float32)
            for s_i in range(8):
                h, c = _lstm_step_np(
                    w, np.asarray(obs, np.float32).reshape(-1), h, c
                )
                a = int(np.argmax(h @ Wp + bp))
                obs, r, te, tr, _ = env.step(a)
            last_correct += int(a == want)
        assert last_correct / trials > 0.9, last_correct / trials
    finally:
        algo.stop()
