"""ISSUE 14 — request waterfalls v2: trace context in the native codec
(severed-tree regression), span coverage, tail-sampled flight recorder,
and metric exemplars.

The core regression test: with the native pump engaged and the
call-frame TEMPLATE path active (i.e. NOT the first call of a shape —
that one ships the full pickled spec and was never broken), a serve
request must still produce ONE connected trace tree
proxy → replica → nested call. The same tree must hold under
``RTPU_NO_NATIVE=1`` (pure-Python compact dict frames) and across a
v1-peer version skew (traceless but functional)."""

import json
import os
import random
import subprocess
import sys
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.core import frame_pump
# ray_tpu.core re-exports the timeline() FUNCTION under this name; the
# tests need the module.
from ray_tpu.core import timeline as _pkg_timeline  # noqa: F401
import ray_tpu.core.timeline
timeline = sys.modules["ray_tpu.core.timeline"]
from ray_tpu.core.rpc import negotiate_codec
from ray_tpu.util import events, flight_recorder
from ray_tpu.util import prometheus as prom
from ray_tpu.util.metrics import _merge_histogram

needs_native = pytest.mark.skipif(
    not frame_pump.available(), reason="native pump extension unavailable"
)


@pytest.fixture
def serve_cluster(ray_tpu_start):
    yield ray_tpu_start
    serve.shutdown()


# --------------------------------------------------------- codec + handshake


def test_negotiate_codec_version_skew():
    """min(offered, supported) with a pickle fallback for junk offers:
    a v2 caller and a v1 worker settle on v1 (traceless native frames),
    never on a dialect one side cannot decode."""
    assert negotiate_codec(2, 2) == 2
    assert negotiate_codec(1, 2) == 1  # v1 peer: settle on v1
    assert negotiate_codec(2, 1) == 1  # we are the v1 side
    assert negotiate_codec(0, 2) == 0
    assert negotiate_codec(None, 2) == 0
    assert negotiate_codec("2", 2) == 0
    assert negotiate_codec(2, 0) == 0


def test_traceless_v2_frame_is_v1_layout():
    """v1-peer-skew parity at the byte level: a v2 encoder with
    trace=None emits exactly the v1 frame layout (hand-packed here), so
    a v1 decoder reads it unchanged."""
    import struct

    tid = b"T" * 16
    frame = frame_pump.py_encode_call(5, tid, 9, 2.5, None, None, None)
    manual = (struct.pack("<BBIQ", frame_pump.MAGIC, frame_pump.F_CALL,
                          5, 9)
              + bytes([16]) + tid + struct.pack("<d", 2.5) + b"\x00")
    assert frame == manual
    assert "tc" not in frame_pump.py_decode(frame)


def test_trace_block_roundtrip_python_mirror():
    tr = ("a" * 32, "b" * 16)
    frame = frame_pump.py_encode_call(5, b"T" * 16, 9, 0.0, None, None,
                                      None, tr)
    assert frame_pump.py_decode(frame)["tc"] == tr
    # Root context: empty parent span id survives the wire.
    frame = frame_pump.py_encode_call(5, b"T" * 16, 9, 0.0, None, None,
                                      None, ("c" * 32, ""))
    assert frame_pump.py_decode(frame)["tc"] == ("c" * 32, "")
    # Unsupported trace shapes refuse (the call falls back to pickle).
    for bad in (("x" * 300, "y"), ("only",), (b"bytes", "y"), "not-a-tuple"):
        assert frame_pump.py_encode_call(
            1, b"T" * 16, 1, 0.0, None, None, None, bad) is None


@needs_native
def test_trace_block_native_refuses_same_shapes():
    mod = frame_pump._module()
    for bad in (("x" * 300, "y"), ("only",), (b"bytes", "y"), "not-a-tuple"):
        assert mod.encode_call(1, b"T" * 16, 1, 0.0, None, None, None,
                               bad) is None


@needs_native
def test_trace_codec_parity_fuzz():
    """Dedicated trace-focused fuzz beside test_native_pump's general
    one: trace present/absent/empty-span, both encoders byte-identical,
    both decoders agree."""
    mod = frame_pump._module()
    rng = random.Random(0x7ACE)
    for _ in range(200):
        trace = rng.choice([
            None,
            (rng.randbytes(16).hex(), rng.randbytes(8).hex()),
            (rng.randbytes(16).hex(), ""),
            ("", ""),
        ])
        tid = rng.randbytes(16)
        nat = mod.encode_call(7, tid, 3, 0.0, None, None, None, trace)
        pyb = frame_pump.py_encode_call(7, tid, 3, 0.0, None, None, None,
                                        trace)
        assert nat == pyb
        d = mod.decode(pyb)
        assert d == frame_pump.py_decode(nat)
        if trace is None:
            assert "tc" not in d
        else:
            assert d["tc"] == trace


# ------------------------------------------------------------ span plumbing


def test_events_carry_trace_context():
    prev = timeline.enter_span("t" * 32, "s" * 16)
    try:
        e = events.make_event(events.INFO, events.WORKER, "probe")
        assert e["trace_id"] is None  # make_event stays pure
        e = events.emit(events.INFO, events.WORKER, "probe")
    finally:
        timeline.exit_span(prev)
    assert e["trace_id"] == "t" * 32
    assert e["span_id"] == "s" * 16
    outside = events.emit(events.INFO, events.WORKER, "probe2")
    assert outside["trace_id"] is None


def test_span_event_requires_active_span():
    buf = timeline.get_buffer()
    with buf._lock:
        before = len(buf._events)
    timeline.span_event("orphan:marker")  # no active span: no record
    with buf._lock:
        assert len(buf._events) == before
    prev = timeline.enter_span("t" * 32, "s" * 16)
    try:
        timeline.span_event("shed:test:unit")
    finally:
        timeline.exit_span(prev)
    with buf._lock:
        evs = list(buf._events)
    marker = [e for e in evs if e["name"] == "shed:test:unit"]
    assert marker and marker[-1]["trace_id"] == "t" * 32
    assert marker[-1]["parent_id"] == "s" * 16


def test_set_enabled_disables_recording():
    buf = timeline.get_buffer()
    prev = timeline.set_enabled(False)
    try:
        with buf._lock:
            before = len(buf._events)
        buf.record("off:probe", 0.0, 1.0, "")
        with buf._lock:
            assert len(buf._events) == before
    finally:
        timeline.set_enabled(prev)


# ------------------------------------------------------- flight recorder


def test_flight_recorder_tail_retention():
    rec = flight_recorder.FlightRecorder(size=32, slow_floor_s=0.5)
    t0 = time.time()
    # Fast, healthy request: dropped.
    assert rec.observe("http:x", "tid-fast", t0, t0 + 0.01,
                       status=200, surface="http") is None
    # Asserted reasons always retain.
    shed = rec.observe("http:x", "tid-shed", t0, t0 + 0.01, status=503,
                       reason="shed", surface="http")
    assert shed and shed["reason"] == "shed"
    exp = rec.observe("http:x", "tid-exp", t0, t0 + 0.02, status=504,
                      reason="expired", surface="http")
    assert exp and exp["reason"] == "expired"
    err = rec.observe("grpc:y", "tid-err", t0, t0 + 0.02,
                      status="INTERNAL", reason="error", surface="grpc")
    assert err and err["reason"] == "error"
    # Slow beyond the floor retains without an asserted reason.
    slow = rec.observe("http:x", "tid-slow", t0, t0 + 2.0, status=200,
                       surface="http")
    assert slow and slow["reason"] == "slow"
    # Chaos note retains immediately.
    rec.note_chaos("direct_channel_io", trace_id="tid-chaos")
    rows = rec.list()
    assert [r["trace_id"] for r in rows] == [
        "tid-shed", "tid-exp", "tid-err", "tid-slow", "tid-chaos"]
    assert [r["trace_id"] for r in rec.list(reason="shed")] == ["tid-shed"]
    assert [r["trace_id"] for r in rec.list(reason="chaos")] == ["tid-chaos"]
    assert rec.stats()["entries"] == 5


def test_flight_recorder_slow_threshold_tracks_p99():
    rec = flight_recorder.FlightRecorder(size=32, slow_floor_s=0.01)
    t0 = time.time()
    # 100 requests around 100ms: the rolling ~p99 rises above the floor,
    # so a 120ms request is NOT slow but a 500ms one is.
    for i in range(100):
        rec.observe("x", f"t{i}", t0, t0 + 0.1, status=200)
    assert rec.slow_threshold_s() >= 0.099
    assert rec.observe("x", "mid", t0, t0 + 0.10, status=200) is None
    kept = rec.observe("x", "outlier", t0, t0 + 0.5, status=200)
    assert kept and kept["reason"] == "slow"


# ------------------------------------------------------------- exemplars


def test_histogram_exemplar_exposition():
    value = {
        "count": 3, "sum": 0.9, "bounds": [0.1, 1.0],
        "buckets": [1, 2, 0],
        "exemplars": {1.0: {"trace_id": "abc123", "value": 0.3,
                            "ts": 1690000000.0}},
    }
    lines = prom._hist_lines("m_seconds", [("deployment", "d")], value)
    joined = "\n".join(lines)
    assert ('m_seconds_bucket{deployment="d",le="1.0"} 3 '
            '# {trace_id="abc123"} 0.3 1690000000.0') in joined
    # Buckets without exemplars render plain.
    assert 'le="0.1"} 1\n' in joined + "\n"


def test_histogram_exemplar_merge_newest_wins():
    a = {"count": 1, "sum": 0.2, "bounds": [1.0], "buckets": [1, 0],
         "exemplars": {1.0: {"trace_id": "old", "value": 0.2, "ts": 1.0}}}
    b = {"count": 1, "sum": 0.3, "bounds": [1.0], "buckets": [1, 0],
         "exemplars": {1.0: {"trace_id": "new", "value": 0.3, "ts": 2.0}}}
    merged = _merge_histogram(a, b)
    assert merged["count"] == 2
    assert merged["exemplars"][1.0]["trace_id"] == "new"
    # Differing bounds rebucket but exemplars survive keyed by `le`.
    c = {"count": 1, "sum": 0.4, "bounds": [0.5, 1.0], "buckets": [0, 1, 0],
         "exemplars": {0.5: {"trace_id": "c", "value": 0.4, "ts": 3.0}}}
    merged = _merge_histogram(merged, c)
    assert merged["exemplars"][1.0]["trace_id"] == "new"
    assert merged["exemplars"][0.5]["trace_id"] == "c"
    # No exemplars on either side -> no key at all.
    plain = _merge_histogram(
        {"count": 1, "sum": 0.1, "bounds": [1.0], "buckets": [1, 0]},
        {"count": 1, "sum": 0.1, "bounds": [1.0], "buckets": [1, 0]})
    assert "exemplars" not in plain


def test_serve_latency_exemplar_lands_in_exposition(serve_cluster):
    from ray_tpu.serve import _telemetry

    _telemetry.observe_ingress("exdep", "http", 200, time.time() - 0.05,
                               trace_id="facefeed" * 4)
    deadline = time.time() + 10
    while time.time() < deadline:
        doc = prom.render()
        if 'trace_id="facefeed' in doc:
            break
        time.sleep(0.3)
    assert 'trace_id="facefeed' in doc
    assert "ray_tpu_serve_request_latency_seconds_bucket" in doc


# ------------------------------------------------- e2e: connected tree


def _post(port, route, payload, timeout=30):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/{route}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read()), dict(resp.headers)


def _spans_for(trace_id, deadline_s=20.0):
    """Poll the cluster span timeline until the full tree for trace_id
    arrived (worker buffers flush on a 0.5s cadence)."""
    deadline = time.time() + deadline_s
    spans = []
    while time.time() < deadline:
        spans = [ev for ev in timeline.timeline()
                 if ev["args"].get("trace_id") == trace_id]
        names = {ev["name"] for ev in spans}
        if (any(n.startswith("http:") for n in names)
                and any(n.endswith("handle_request")
                        and not n.startswith("queue:") for n in names)
                and any(n.endswith(".work") for n in names)
                and any(n.startswith("queue:") for n in names)):
            return spans
        time.sleep(0.4)
    return spans


def test_connected_trace_tree(serve_cluster):
    """THE severed-tree regression: after the call-frame template is
    warm (request >= 2 rides the compact/native dialect), a serve
    request still yields one connected proxy → replica → nested tree,
    and the response's traceparent header names that same trace."""

    @serve.deployment
    class Parent:
        def __init__(self):
            @ray_tpu.remote
            class Nested:
                def work(self, x):
                    return x + 1

            self.nested = Nested.remote()

        def __call__(self, x):
            return ray_tpu.get(self.nested.work.remote(x))

    from ray_tpu.core.runtime_context import current_runtime

    handle = serve.run(Parent.bind(), name="par", route_prefix="par")
    port = handle.http_port
    # Wait for the replica's DIRECT channel (discovery is async; until
    # then requests ride the NM path, which was never severed), then
    # warm the template path: the FIRST direct call of a shape ships the
    # full pickled spec — only LATER calls ride the compact frame this
    # PR fixes.
    rt = current_runtime()
    deadline = time.time() + 30
    i = 0
    while time.time() < deadline:
        body, headers = _post(port, "par", i)
        assert body == {"result": i + 1}
        i += 1
        if any(st.get("status") == "ready"
               for st in rt._direct_states.values()):
            break
        time.sleep(0.05)
    assert any(st.get("status") == "ready"
               for st in rt._direct_states.values()), (
        "direct channel never engaged")
    for _ in range(2):
        body, headers = _post(port, "par", i)
        assert body == {"result": i + 1}
        i += 1
    tp = headers.get("traceparent", "")
    assert tp.startswith("00-"), f"no traceparent response header: {headers}"
    trace_id = tp.split("-")[1]

    spans = _spans_for(trace_id)
    by_name = {}
    for ev in spans:
        by_name.setdefault(ev["name"], []).append(ev)
    assert any(n.startswith("http:") for n in by_name), (
        f"no ingress root span for {trace_id}: {sorted(by_name)}")
    root = next(v[0] for k, v in by_name.items() if k.startswith("http:"))
    assert root["args"]["parent_id"] == ""
    replica_names = [n for n in by_name
                     if n.endswith("handle_request")
                     and not n.startswith("queue:")]
    assert replica_names, (
        f"replica span missing — tree severed at the codec: "
        f"{sorted(by_name)}")
    replica = by_name[replica_names[0]][-1]
    assert replica["args"]["parent_id"] == root["args"]["span_id"], (
        "replica span not parented to the ingress root")
    nested_names = [n for n in by_name if n.endswith(".work")]
    assert nested_names, (
        f"nested span missing — tree severed below the replica: "
        f"{sorted(by_name)}")
    nested = by_name[nested_names[0]][-1]
    assert nested["args"]["parent_id"] == replica["args"]["span_id"], (
        "nested span not parented to the replica span")
    # Queue-wait/execution split: the replica call carries a sibling
    # queue: span under the same parent.
    queue_names = [n for n in by_name if n.startswith("queue:")
                   and n.endswith("handle_request")]
    assert queue_names, f"no queue-wait span: {sorted(by_name)}"
    q = by_name[queue_names[0]][-1]
    assert q["args"]["parent_id"] == root["args"]["span_id"]


@needs_native
def test_connected_tree_channel_negotiated_v2(serve_cluster):
    """The tree test above plus the explicit channel assertion: the
    replica's direct channel engaged the pump AND negotiated codec v2
    (trace context rides the native frames, not pickle)."""
    from ray_tpu.core.runtime_context import current_runtime

    @serve.deployment
    def echo(x):
        return x

    handle = serve.run(echo.bind(), name="npv2", route_prefix="npv2")
    rt = current_runtime()
    # Direct-channel discovery is async: keep issuing requests until a
    # channel is ready and pump-engaged (the _engage idiom).
    native = []
    deadline = time.time() + 30
    i = 0
    while time.time() < deadline and not native:
        body, _headers = _post(handle.http_port, "npv2", i)
        assert body == {"result": i}
        i += 1
        native = [
            st for st in rt._direct_states.values()
            if st.get("status") == "ready" and st.get("chan") is not None
            and getattr(st["chan"], "native", False)
        ]
        time.sleep(0.05)
    assert native, "no direct channel engaged the native pump"
    assert any(getattr(st["chan"], "npv", 0) >= frame_pump.TRACE_MIN_VER
               for st in native), (
        "native channel negotiated npv < 2: trace context cannot ride "
        "the codec")


def test_connected_trace_tree_forced_fallback():
    """RTPU_NO_NATIVE=1: the same connected tree over the pure-Python
    compact dict frames (the 'tc' field on the pickle dialect)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["RTPU_NO_NATIVE"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-m", "pytest",
         "tests/test_trace_waterfalls.py::test_connected_trace_tree",
         "-q", "-p", "no:cacheprovider"],
        cwd=repo, env=env, capture_output=True, timeout=300, text=True,
    )
    assert proc.returncode == 0, (
        f"connected-tree test failed under RTPU_NO_NATIVE=1:\n"
        f"{proc.stdout[-4000:]}\n{proc.stderr[-2000:]}"
    )


# ------------------------------------- recorder surfaces on a live cluster


def test_flight_recorder_cluster_surfaces(serve_cluster):
    """A shed record is retrievable through every surface: the local
    ring, the KV-merged list_cluster, the GCS traces_dump fan-out, and
    the waterfall join."""
    from ray_tpu.core.runtime_context import current_runtime

    t0 = time.time()
    trace_id = "beadfeed" * 4
    prev = timeline.enter_span(trace_id, "")
    try:
        timeline.record_span("http:probe", t0, t0 + 0.01,
                             parent=(trace_id, ""))
    finally:
        timeline.exit_span(prev)
    flight_recorder.observe_request(
        "http:probe", trace_id, t0, t0 + 0.01, status=503,
        reason="shed", surface="http",
    )
    rows = flight_recorder.list_cluster(reason="shed", limit=50)
    assert any(r["trace_id"] == trace_id for r in rows)
    reply = current_runtime().cluster_traces(reason="shed")
    assert reply.get("errors") == {}
    found = [r for node in reply["nodes"]
             for r in node.get("records", ())
             if r.get("trace_id") == trace_id]
    assert found, f"traces_dump fan-out missed the record: {reply}"
    tree = flight_recorder.waterfall(trace_id)
    assert any(s["name"] == "http:probe" for s in tree["spans"])
    assert any(r["reason"] == "shed" for r in tree["records"])
    text = flight_recorder.format_waterfall(tree)
    assert trace_id in text and "http:probe" in text


def test_shed_request_retained_with_trace(serve_cluster):
    """End to end through the proxy: an admission-gate shed (503) leaves
    a retrievable flight-recorder record whose trace id matches the
    traceparent the CLIENT saw on the 503 response, with the gate's
    decision recorded as a span event in the request's waterfall."""
    from ray_tpu.serve import http_proxy
    from ray_tpu.util import overload

    @serve.deployment
    def slowpoke(x):
        time.sleep(0.2)
        return x

    handle = serve.run(slowpoke.bind(), name="shedme",
                       route_prefix="shedme")
    port = handle.http_port
    # Force the gate shut: a permanently-full limiter + empty queue.
    gate = http_proxy._gates.get("shedme")
    tiny = overload.AdmissionGate(
        overload.AIMDLimiter(initial=1, min_limit=1, max_limit=1),
        max_queue=0,
    )
    tiny.limiter._inflight = 1
    http_proxy._gates._gates["shedme"] = tiny
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/shedme",
            data=json.dumps(1).encode(),
            headers={"Content-Type": "application/json"},
        )
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            urllib.request.urlopen(req, timeout=30)
        err = exc_info.value
        assert err.code == 503
        assert err.headers.get("Retry-After")
        tp = err.headers.get("traceparent", "")
        assert tp.startswith("00-")
        trace_id = tp.split("-")[1]
    finally:
        http_proxy._gates._gates["shedme"] = gate
    # The record lands in do_POST's finally, which runs just after the
    # client got its 503: poll briefly instead of racing it.
    deadline = time.time() + 10
    rows = []
    while time.time() < deadline:
        rows = flight_recorder.list_cluster(reason="shed", limit=50,
                                            include_gcs=False)
        if any(r["trace_id"] == trace_id for r in rows):
            break
        time.sleep(0.2)
    assert any(r["trace_id"] == trace_id for r in rows), (
        f"shed request {trace_id} not retained: {rows[-5:]}")
    # The admission-gate decision is a span event in the waterfall.
    deadline = time.time() + 10
    while time.time() < deadline:
        tree = flight_recorder.waterfall(trace_id)
        if any(s["name"].startswith("shed:proxy")
               for s in tree["spans"]):
            break
        time.sleep(0.4)
    assert any(s["name"].startswith("shed:proxy")
               for s in tree["spans"]), tree["spans"]
