"""GBDT trainer + predictor/batch-inference tests (ref analogue:
python/ray/train/tests/test_xgboost_trainer.py + test_batch_predictor)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rd
from ray_tpu.train import BatchPredictor, GBDTPredictor, GBDTTrainer
from ray_tpu.train.config import RunConfig


def _make_ds(n=400, seed=0):
    rs = np.random.RandomState(seed)
    x0 = rs.randn(n)
    x1 = rs.randn(n)
    y = ((x0 + 0.5 * x1) > 0).astype(np.int64)
    return rd.from_items(
        [{"x0": float(x0[i]), "x1": float(x1[i]), "label": int(y[i])}
         for i in range(n)],
        override_num_blocks=4,
    )


def test_gbdt_train_and_predict(ray_tpu_start, tmp_path):
    ds = _make_ds()
    trainer = GBDTTrainer(
        datasets={"train": ds},
        label_column="label",
        params={"max_iter": 30},
        run_config=RunConfig(storage_path=str(tmp_path)),
    )
    result = trainer.fit()
    assert result.metrics["train_score"] > 0.9
    assert result.checkpoint is not None

    predictor = GBDTPredictor.from_checkpoint(result.checkpoint)
    batch = {"x0": np.asarray([2.0, -2.0]), "x1": np.asarray([0.0, 0.0])}
    preds = predictor.predict(batch)["predictions"]
    assert list(preds) == [1, 0]


@pytest.mark.slow
def test_batch_predictor_over_dataset(ray_tpu_start, tmp_path):
    ds = _make_ds()
    result = GBDTTrainer(
        datasets={"train": ds},
        label_column="label",
        params={"max_iter": 20},
        run_config=RunConfig(storage_path=str(tmp_path)),
    ).fit()

    bp = BatchPredictor(result.checkpoint, GBDTPredictor)
    scored = bp.predict(ds.drop_columns(["label"]), concurrency=2)
    preds = scored.to_numpy()["predictions"]
    truth = ds.to_numpy()
    acc = (preds == ((truth["x0"] + 0.5 * truth["x1"]) > 0)).mean()
    assert acc > 0.9
