"""SLO plane tests (ISSUE 16): TSDB ring/rate/quantile units, series-cap
drop accounting, multi-window burn-rate math against a synthetic trace,
SLO alert-event dedup, `__metrics__` blob GC, and the e2e acceptance
scenario — chaos-injected latency on one replica drives a fast-window
burn alert and an SLO-signalled scale-up; heal decays the burn and the
deployment scales back.
"""

import time

import pytest

import ray_tpu
from ray_tpu.util import slo
from ray_tpu.util import state as state_api
from ray_tpu.util.tsdb import TSDB, fraction_le, quantile_from_histogram


def _poll(fn, timeout=15.0, interval=0.2):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        out = fn()
        if out:
            return out
        time.sleep(interval)
    return fn()


# ------------------------------------------------------------ TSDB units


def test_tsdb_ring_bound_and_query_shapes():
    tsdb = TSDB(samples_per_series=8, max_series=10)
    for i in range(50):
        tsdb.ingest("c", "counter", (("a", "1"),), float(i), 100.0 + i)
    st = tsdb.stats()
    assert st["series"] == 1
    assert st["samples"] == 8  # ring dropped the old 42
    rows = tsdb.query("c")
    assert len(rows) == 1
    assert rows[0]["tags"] == [["a", "1"]]
    assert rows[0]["samples"][0] == [142.0, 42.0]  # oldest survivor
    assert rows[0]["samples"][-1] == [149.0, 49.0]
    # since/limit trims.
    assert len(tsdb.query("c", since=148.0)[0]["samples"]) == 2
    assert len(tsdb.query("c", limit=3)[0]["samples"]) == 3
    assert tsdb.names() == ["c"]
    assert tsdb.latest("c") == 49.0


def test_tsdb_series_cap_drops_counted():
    tsdb = TSDB(samples_per_series=4, max_series=3)
    for i in range(5):
        tsdb.ingest("g", "gauge", (("i", str(i)),), 1.0, float(i))
    st = tsdb.stats()
    assert st["series"] == 3
    assert st["dropped"] == 2
    # Existing series still ingest under the cap.
    assert tsdb.ingest("g", "gauge", (("i", "0"),), 2.0, 9.0)
    assert tsdb.stats()["dropped"] == 2
    # Memory bound: series x samples_per_series is the hard ceiling.
    for i in range(100):
        tsdb.ingest("g", "gauge", (("i", "0"),), float(i), 10.0 + i)
    assert tsdb.stats()["samples"] <= 3 * 4


def test_tsdb_rate_counter_reset_robust():
    tsdb = TSDB()
    # 1/s counter that resets (process restart) mid-window.
    for ts, v in [(0, 0.0), (10, 10.0), (20, 20.0), (30, 0.0),
                  (40, 10.0)]:
        tsdb.ingest("c", "counter", (), v, float(ts))
    # Increase: 10 + 10 + (reset: clamped to 0) + 10 = 30 over 40s.
    assert tsdb.delta("c", window_s=40.0, now=40.0) == 30.0
    assert tsdb.rate("c", window_s=40.0, now=40.0) == pytest.approx(0.75)
    # No samples in window -> None, not 0.
    assert tsdb.delta("missing", window_s=10.0, now=40.0) is None


def test_quantile_and_fraction_helpers():
    bounds = [0.1, 0.5, 1.0]
    buckets = [50.0, 30.0, 15.0, 5.0]  # last = +Inf overflow
    assert quantile_from_histogram(bounds, buckets, 0.5) == \
        pytest.approx(0.1)
    # p80 = exactly the 0.5 bound (50+30 of 100).
    assert quantile_from_histogram(bounds, buckets, 0.8) == \
        pytest.approx(0.5)
    # Overflow clamps to the last finite bound.
    assert quantile_from_histogram(bounds, buckets, 0.999) == 1.0
    assert quantile_from_histogram(bounds, [0, 0, 0, 0], 0.5) is None
    assert fraction_le(bounds, buckets, 0.5) == pytest.approx(0.8)
    # Interpolated inside the (0.1, 0.5] bucket.
    assert fraction_le(bounds, buckets, 0.3) == pytest.approx(0.65)
    assert fraction_le(bounds, buckets, 99.0) == pytest.approx(
        0.95, abs=1e-6)


def test_tsdb_hist_delta_window_quantile():
    tsdb = TSDB()
    bounds = (0.1, 1.0)
    cum = [0.0, 0.0, 0.0]
    count = total = 0.0
    for i in range(20):
        fast = i < 10  # first 10s fast, last 10s slow
        cum[0 if fast else 1] += 10
        count += 10
        total += 10 * (0.05 if fast else 0.5)
        tsdb.ingest(
            "h", "histogram", (("deployment", "d"),),
            {"count": count, "sum": total, "bounds": bounds,
             "buckets": list(cum)},
            float(i),
        )
    # Window over the slow half only.
    q = tsdb.quantile("h", 0.5, {"deployment": "d"}, window_s=9.0,
                      now=19.0)
    assert q is not None and q > 0.1
    d = tsdb.hist_delta("h", {"deployment": "d"}, window_s=9.0, now=19.0)
    # 10 in-window samples plus the pre-window baseline -> 10 deltas.
    assert d["count"] == pytest.approx(100.0)
    # Nearly all window mass sits above the 0.1 bound (a sliver leaks
    # below via linear interpolation: empty buckets drop out of the
    # delta map, widening the containing bucket).
    assert fraction_le(d["bounds"], d["buckets"], 0.1) < 0.15


# ------------------------------------------------------------ spec + engine


def test_normalize_spec_validates_and_defaults():
    spec = slo.normalize_spec({})
    assert spec["latency_target_s"] == 0.5
    assert spec["objective"] == pytest.approx(0.999 + 0.99 - 1.0)
    assert spec["windows"]["fast"] == [300.0, 3600.0]
    assert spec["burn_thresholds"]["slow"] == 6.0
    with pytest.raises(ValueError):
        slo.normalize_spec({"latency_target": 0.5})  # typo'd key
    with pytest.raises(ValueError):
        slo.normalize_spec({"latency_percentile": 1.5})
    with pytest.raises(ValueError):
        slo.normalize_spec({"windows": {"fast": [10, 5]}})
    with pytest.raises(ValueError):
        slo.normalize_spec("p99<0.5s")  # not a dict


def _synthetic_trace(tsdb, t0, ticks, bad, bounds, state):
    """Append `ticks` x 0.5s of traffic: 5 requests per tick, all fast
    (first bucket) or all slow (third bucket)."""
    for i in range(ticks):
        state["cum"][2 if bad else 0] += 5
        state["count"] += 5
        state["sum"] += 5 * (0.7 if bad else 0.05)
        tsdb.ingest(
            "ray_tpu_serve_replica_processing_seconds", "histogram",
            (("deployment", "d"), ("method", "__call__")),
            {"count": state["count"], "sum": state["sum"],
             "bounds": bounds, "buckets": list(state["cum"])},
            t0 + i * 0.5,
        )
    return t0 + ticks * 0.5


def test_burn_rate_windows_and_event_dedup():
    """Multi-window math on a synthetic trace: good traffic burns ~0;
    an outage fires BOTH pairs (short AND long over threshold) exactly
    once; recovery clears the fast pair (short windows decay first)
    while the slow pair keeps firing — and repeated evaluation while a
    condition persists emits nothing new."""
    tsdb = TSDB()
    spec = slo.normalize_spec({
        "latency_target_s": 0.1,
        "windows": {"fast": [10, 20], "slow": [30, 60]},
    })
    emitted = []
    eng = slo.SloEngine(
        emit_event=lambda sev, msg, f: emitted.append((sev, f)))
    bounds = (0.05, 0.1, 1.0)
    st = {"cum": [0.0, 0.0, 0.0, 0.0], "count": 0.0, "sum": 0.0}
    budget = 1.0 - spec["objective"]

    # 60s of good traffic: goodput 1.0, burn 0, no events.
    t = _synthetic_trace(tsdb, 1000.0, 120, False, bounds, st)
    status = eng.evaluate(tsdb, {"d": spec}, t)
    assert status["d"]["goodput"]["10"] == pytest.approx(1.0)
    assert status["d"]["burn"]["60"] == pytest.approx(0.0)
    assert status["d"]["budget_remaining"] == pytest.approx(1.0)
    assert not status["d"]["fast_burn_active"]
    assert emitted == []

    # 10s outage: every request lands over the target.
    t = _synthetic_trace(tsdb, t, 20, True, bounds, st)
    status = eng.evaluate(tsdb, {"d": spec}, t)
    # Short fast window (10s) is ~all bad; long fast window (20s) half
    # bad — both far over the 14.4x page threshold for a 98.9% budget.
    assert status["d"]["burn"]["10"] == pytest.approx(
        1.0 / budget, rel=0.15)
    assert status["d"]["burn"]["20"] == pytest.approx(
        0.5 / budget, rel=0.15)
    assert status["d"]["fast_burn_active"]
    assert status["d"]["slow_burn_active"]
    assert status["d"]["budget_remaining"] < 1.0
    warns = [(sev, f) for sev, f in emitted if sev == "WARNING"]
    assert sorted(f["pair"] for _, f in warns) == ["fast", "slow"]
    # Condition persists: re-evaluation stays silent (dedup).
    eng.evaluate(tsdb, {"d": spec}, t)
    eng.evaluate(tsdb, {"d": spec}, t)
    assert len(emitted) == 2

    # 25s of recovery: the fast pair's windows (10/20s) are clean again
    # -> one INFO clear; the slow 30/60s windows still see the outage
    # -> slow keeps firing, silently.
    t = _synthetic_trace(tsdb, t, 50, False, bounds, st)
    status = eng.evaluate(tsdb, {"d": spec}, t)
    assert not status["d"]["fast_burn_active"]
    assert status["d"]["slow_burn_active"]
    clears = [(sev, f) for sev, f in emitted if sev == "INFO"]
    assert [f["pair"] for _, f in clears] == ["fast"]
    assert len(emitted) == 3

    # A vanished spec drops its alert state (no stale clears later).
    eng.evaluate(tsdb, {}, t)
    assert eng.status == {}
    assert len(emitted) == 3


def test_decode_specs_and_read_status_tolerate_garbage():
    good = slo.normalize_spec({"latency_target_s": 0.2})
    import json

    items = {
        f"{slo.SPEC_PREFIX}ok": json.dumps(good).encode(),
        f"{slo.SPEC_PREFIX}corrupt": b"\x80not-json",
        f"{slo.SPEC_PREFIX}unnormalized": b"{}",  # no objective
    }
    specs = slo.decode_specs(items)
    assert list(specs) == ["ok"]
    assert specs["ok"]["latency_target_s"] == 0.2
    assert slo.read_status(lambda k: None) == {}
    assert slo.read_status(lambda k: b"junk{") == {}


# ----------------------------------------------------- cluster integration


def test_metrics_blob_gc_and_timeseries_rpc(ray_tpu_start):
    """The head sampler GCs `__metrics__` blobs whose writer is dead
    (unknown node / stale ts) after the grace window, keeps live ones,
    and serves the TSDB over the timeseries_query RPC."""
    import cloudpickle

    from ray_tpu.core.gcs import GcsService
    from ray_tpu.core.runtime_context import current_runtime
    from ray_tpu.util.metrics import KV_PREFIX

    rt = current_runtime()
    old_grace = GcsService.METRICS_GC_GRACE_S
    GcsService.METRICS_GC_GRACE_S = 0.5
    try:
        dead_key = f"{KV_PREFIX}deadbeef00/12345"
        rt.kv_put(dead_key, cloudpickle.dumps({
            "v": 2, "ts": time.time(), "pid": 12345,
            "node": "deadbeef00",
            "metrics": {"ghost_gauge": ("gauge", {(): 1.0}, "")},
        }))
        stale_key = f"{KV_PREFIX}54321"
        rt.kv_put(stale_key, cloudpickle.dumps({
            "v": 2, "ts": time.time() - 3600.0, "pid": 54321, "node": "",
            "metrics": {},
        }))
        assert _poll(
            lambda: dead_key not in rt.kv_keys(KV_PREFIX)
            and stale_key not in rt.kv_keys(KV_PREFIX)
        ), "dead writers' blobs must be reaped past the grace window"
        # A live writer's blob shows up (proc-stats sampler / head
        # publisher cadence is ~5s) and survives the same GC passes.
        assert _poll(lambda: rt.kv_keys(KV_PREFIX), timeout=20.0)
        # And the sampler has been feeding the TSDB: discovery form.
        disc = _poll(lambda: (rt.timeseries_query() or {})
                     if (rt.timeseries_query().get("names")) else None)
        assert disc["stats"]["series"] >= 1
        assert disc["stats"]["dropped"] == 0
        name = disc["names"][0]
        series = rt.timeseries_query(name=name)["series"]
        assert series and series[0]["samples"]
    finally:
        GcsService.METRICS_GC_GRACE_S = old_grace


@pytest.fixture
def slo_cluster():
    """Cluster with a fast SLO eval cadence for the e2e loop."""
    from ray_tpu import serve
    from ray_tpu.util import faults

    rt = ray_tpu.init(
        num_cpus=4,
        system_config={
            "num_prestart_workers": 2,
            "slo_eval_interval_s": 0.5,
        },
    )
    yield rt
    try:
        nm = rt._nm
        nm.call_sync(nm._gcs.chaos_arm([]), timeout=30)
    except Exception:
        pass
    faults.clear()
    serve.shutdown()
    ray_tpu.shutdown()


def test_slo_e2e_chaos_burn_alert_scale_up_and_recovery(slo_cluster):
    """THE acceptance loop: latency chaos on the only replica of an
    SLO'd deployment -> goodput collapses -> the fast burn pair fires
    (WARNING `SLO` event, nominally within 2 eval intervals of the
    window filling) -> the controller scales up on the SLO signal
    (queue depth alone would never trigger here); disarm -> burn decays
    -> INFO clear and the deployment scales back down."""
    import threading

    from ray_tpu import serve
    from ray_tpu.serve.deployment import AutoscalingConfig

    rt = slo_cluster

    @serve.deployment(
        num_replicas=1, max_concurrent_queries=4,
        ray_actor_options={"max_concurrency": 4},
        autoscaling_config=AutoscalingConfig(
            min_replicas=1, max_replicas=3,
            # Queue depth can't ask for more capacity: any upscale must
            # come from the SLO burn signal.
            target_ongoing_requests=1000.0,
            upscale_delay_s=0.5, downscale_delay_s=1.0,
        ),
        slo={
            "latency_target_s": 0.1,
            "windows": {"fast": [2.0, 4.0], "slow": [3.0, 6.0]},
            # The slow (ticket) pair is effectively disabled so the
            # test exercises exactly one alert pair.
            "burn_thresholds": {"fast": 1.5, "slow": 1e9},
        },
    )
    class Echo:
        def __call__(self, req):
            return req

    handle = serve.run(Echo.bind(), name="slo-echo")
    assert serve.details()["slo-echo"]["slo"]["latency_target_s"] == 0.1

    stop = threading.Event()

    def drive():
        i = 0
        while not stop.is_set():
            futs = [handle.remote(i + j) for j in range(3)]
            for f in futs:
                try:
                    f.result(timeout=30)
                except Exception:
                    pass
            i += 3

    driver = threading.Thread(target=drive, daemon=True)
    driver.start()
    try:
        # Baseline: traffic meets the target, no burn, no alert.
        status = _poll(
            lambda: (rt.slo_status()["deployments"] or {}).get("slo-echo")
        )
        assert status, "engine must evaluate the declared spec"
        assert not status["fast_burn_active"]

        # Inject 0.5s latency into the (only) replica.
        stats = ray_tpu.get(
            [r.stats.remote() for r in list(handle._state.replicas)],
            timeout=30,
        )
        sick_id = stats[0]["replica_id"]
        nm = rt._nm
        nm.call_sync(nm._gcs.chaos_arm([{
            "point": "serve_replica", "mode": "always",
            "action": "latency", "delay_s": 0.5,
            "match": {"replica": sick_id},
        }]), timeout=30)

        # Fast-window burn alert fires as a WARNING `SLO` event...
        ev = _poll(lambda: [
            e for e in state_api.list_cluster_events(source="SLO")
            if e["severity"] == "WARNING"
            and e.get("custom_fields", {}).get("pair") == "fast"
        ], timeout=20.0)
        assert ev, "fast burn alert must fire under injected latency"
        assert ev[0]["custom_fields"]["deployment"] == "slo-echo"
        # ...the burn gauges ride the normal metrics pipeline...
        status = rt.slo_status()["deployments"]["slo-echo"]
        assert status["fast_burn_active"]
        assert max(status["burn"].values()) > 1.5
        # ...and the controller scales up on the SLO signal.
        assert _poll(
            lambda: serve.details()["slo-echo"]["target_replicas"] >= 2,
            timeout=20.0,
        ), "controller must add capacity on a fast-window burn"

        # Heal: burn decays, the alert clears, capacity returns.
        nm.call_sync(nm._gcs.chaos_arm([]), timeout=30)
        assert _poll(lambda: [
            e for e in state_api.list_cluster_events(source="SLO")
            if e["severity"] == "INFO"
            and e.get("custom_fields", {}).get("pair") == "fast"
        ], timeout=30.0), "alert must clear after heal"
        assert _poll(
            lambda: serve.details()["slo-echo"]["target_replicas"] == 1,
            timeout=30.0,
        ), "capacity must return once the burn is gone"
    finally:
        stop.set()
        driver.join(timeout=10)
